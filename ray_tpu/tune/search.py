"""Search spaces + searchers.

Reference analog: ``python/ray/tune/search/`` — the sampling primitives
(``tune.uniform/choice/...``), ``grid_search``, and
``BasicVariantGenerator`` (grid expansion × num_samples random sampling).
External searcher integrations (optuna/hyperopt/...) plug in behind the
same ``suggest`` interface."""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]
    # bounds for numeric domains (None for categorical): adaptive
    # searchers clamp proposals to [low, high]
    low: float | None = None
    high: float | None = None
    integer: bool = False

    def sample(self, rng: random.Random):
        return self.sampler(rng)

    def clamp(self, x):
        if self.low is not None:
            x = max(x, self.low)
        if self.high is not None:
            x = min(x, self.high)
        if self.integer:
            hi = self.high - 1 if self.high is not None else None
            x = int(round(x))
            if self.low is not None:
                x = max(x, int(self.low))
            if hi is not None:
                x = min(x, int(hi))
        return x


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high), low=low, high=high)


def loguniform(low: float, high: float) -> Domain:
    import math

    return Domain(lambda rng: math.exp(
        rng.uniform(math.log(low), math.log(high))), low=low, high=high)


def randint(low: int, high: int) -> Domain:
    """Samples from [low, high) like the reference's tune.randint."""
    return Domain(lambda rng: rng.randrange(low, high), low=low, high=high,
                  integer=True)


def choice(options: list) -> Domain:
    options = list(options)
    return Domain(lambda rng: rng.choice(options))


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


class BasicVariantGenerator:
    """Expands grid_search axes (cartesian product) and samples Domains;
    ``num_samples`` repeats the whole expansion (reference semantics)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> list[dict]:
        grid_keys = []
        grid_values = []

        def walk(prefix, node):
            for k, v in node.items():
                path = prefix + (k,)
                if isinstance(v, dict) and "grid_search" in v:
                    grid_keys.append(path)
                    grid_values.append(v["grid_search"])
                elif isinstance(v, dict):
                    walk(path, v)

        walk((), self.param_space)
        combos = list(itertools.product(*grid_values)) if grid_values else [()]
        out = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg = self._sample(self.param_space)
                for path, value in zip(grid_keys, combo):
                    _set_path(cfg, path, value)
                out.append(cfg)
        return out

    def _sample(self, node: dict) -> dict:
        cfg = {}
        for k, v in node.items():
            if isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            elif isinstance(v, dict) and "grid_search" in v:
                cfg[k] = None  # filled by the grid combo
            elif isinstance(v, dict):
                cfg[k] = self._sample(v)
            else:
                cfg[k] = v
        return cfg


def _set_path(cfg: dict, path: tuple, value):
    node = cfg
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value


# ---------------------------------------------------------------------------
# adaptive searchers (reference: tune/search/searcher.py Searcher interface;
# hyperopt/optuna integrations plug in behind suggest/on_trial_complete)
# ---------------------------------------------------------------------------

class Searcher:
    """suggest(trial_id) -> config | None (None = no budget left);
    on_trial_result / on_trial_complete feed observations back."""

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False):
        pass


class TPESearcher(Searcher):
    """Tree-structured-Parzen-Estimator-style adaptive search over a
    Domain/grid-free param space (the native analog of the reference's
    hyperopt integration, ``tune/search/hyperopt/``).

    After ``n_startup`` random trials, numeric dimensions are proposed by
    sampling candidates and scoring them by the ratio of Gaussian-kernel
    densities fit to the good (top gamma quantile) vs bad observations;
    categorical dimensions are drawn from smoothed good-split counts.
    """

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 num_samples: int = 32, n_startup: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        self.space = space
        self.metric = metric
        self.mode = mode
        self.budget = num_samples
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._suggested = 0
        self._obs: dict[str, tuple[dict, float]] = {}  # id -> (cfg, score)
        self._configs: dict[str, dict] = {}            # id -> suggested cfg

    # -- observations ---------------------------------------------------

    def on_trial_complete(self, trial_id, result=None, error=False):
        if error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        cfg = self._configs.get(trial_id)
        if cfg is not None:
            self._obs[trial_id] = (cfg, score)

    # -- proposals ------------------------------------------------------

    def suggest(self, trial_id: str) -> dict | None:
        if self._suggested >= self.budget:
            return None
        self._suggested += 1
        if len(self._obs) < self.n_startup:
            cfg = self._random_config()
        else:
            cfg = self._tpe_config()
        self._configs[trial_id] = cfg
        return cfg

    def _random_config(self) -> dict:
        return {k: (v.sample(self.rng) if isinstance(v, Domain) else v)
                for k, v in self.space.items()}

    def _split_obs(self):
        obs = sorted(self._obs.values(), key=lambda cv: cv[1],
                     reverse=(self.mode == "max"))
        n_good = max(1, int(len(obs) * self.gamma))
        return obs[:n_good], obs[n_good:]

    def _tpe_config(self) -> dict:
        good, bad = self._split_obs()
        cfg = {}
        for k, dom in self.space.items():
            if not isinstance(dom, Domain):
                cfg[k] = dom
                continue
            gvals = [c[k] for c, _ in good if k in c]
            bvals = [c[k] for c, _ in bad if k in c]
            sample = dom.sample(self.rng)
            if isinstance(sample, (int, float)) and not isinstance(
                    sample, bool) and gvals and all(
                    isinstance(v, (int, float)) for v in gvals):
                cfg[k] = self._propose_numeric(dom, gvals, bvals,
                                               integer=isinstance(sample, int))
            elif gvals:
                cfg[k] = self._propose_categorical(dom, gvals)
            else:
                cfg[k] = sample
        return cfg

    def _kde(self, x: float, centers: list, bw: float) -> float:
        import math

        if not centers:
            return 1e-12
        return sum(math.exp(-0.5 * ((x - c) / bw) ** 2)
                   for c in centers) / (len(centers) * bw)

    def _propose_numeric(self, dom: Domain, gvals, bvals, *, integer):
        lo = min(gvals + bvals)
        hi = max(gvals + bvals)
        bw = max((hi - lo) / 4.0, 1e-9)
        best, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            # good-centered Gaussian mixture + prior samples; every
            # candidate is clamped into the domain's declared bounds
            # (a raw gauss() draw can land outside [low, high])
            if self.rng.random() < 0.75 and gvals:
                x = dom.clamp(self.rng.gauss(self.rng.choice(gvals), bw))
            else:
                x = dom.sample(self.rng)
            ratio = self._kde(x, gvals, bw) / (
                self._kde(x, bvals, bw) + 1e-12)
            if ratio > best_ratio:
                best, best_ratio = x, ratio
        if integer:
            best = dom.clamp(best)
        return best

    def _propose_categorical(self, dom: Domain, gvals):
        # smoothed counts over the good split; fall back to the prior
        # for unseen options by mixing one prior sample in
        counts: dict = {}
        for v in gvals:
            counts[v] = counts.get(v, 0) + 1
        options = list(counts) + [dom.sample(self.rng)]
        weights = [counts.get(o, 0) + 0.5 for o in options]
        return self.rng.choices(options, weights=weights, k=1)[0]


class BOHBSearcher(TPESearcher):
    """BOHB-style model-based search: TPE models fit PER TRAINING BUDGET
    (training_iteration), proposals drawn from the largest budget with
    enough observations. Pair with ``HyperBandScheduler`` — together they
    are the native analog of the reference's TuneBOHB + HpBandSter
    (``tune/search/bohb/``): HyperBand allocates budgets and promotes,
    BOHB replaces its random sampling with a model.

        tuner = Tuner(train_fn, param_space=space,
                      tune_config=TuneConfig(
                          search_alg=BOHBSearcher(space, metric="loss",
                                                  mode="min", num_samples=32),
                          scheduler=HyperBandScheduler(metric="loss",
                                                       mode="min")))
    """

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 num_samples: int = 32, n_startup: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        super().__init__(space, metric=metric, mode=mode,
                         num_samples=num_samples, n_startup=n_startup,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        # budget (training_iteration) -> {trial_id: (cfg, score)}
        self._obs_by_budget: dict[int, dict] = {}

    def _record(self, trial_id: str, result: dict):
        if not result or self.metric not in result:
            return
        cfg = self._configs.get(trial_id)
        if cfg is None:
            return
        budget = int(result.get("training_iteration", 1))
        level = self._obs_by_budget.setdefault(budget, {})
        level[trial_id] = (cfg, float(result[self.metric]))

    def on_trial_result(self, trial_id, result):
        # BOHB's point: partial results at rung boundaries feed the model
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        if not error and result:
            self._record(trial_id, result)

    def suggest(self, trial_id: str) -> dict | None:
        # model on the LARGEST budget with enough observations (BOHB rule)
        self._obs = {}
        for budget in sorted(self._obs_by_budget, reverse=True):
            level = self._obs_by_budget[budget]
            if len(level) >= self.n_startup:
                self._obs = dict(level)
                break
        return super().suggest(trial_id)


class BayesOptSearcher(Searcher):
    """Gaussian-process Bayesian optimization with expected improvement
    (the native analog of the reference's bayes_opt integration,
    ``tune/search/bayesopt/``). Numeric Domains only get modeled;
    categorical/static keys fall back to prior sampling.

    A full numpy GP: RBF kernel on [0,1]-normalized inputs, Cholesky
    solve, EI acquisition maximized over random candidates. No external
    optimizer dependency — the whole loop is a few dense solves, which
    is the right tool at tune-scale trial counts (tens to hundreds)."""

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 num_samples: int = 32, n_startup: int = 6,
                 n_candidates: int = 256, length_scale: float = 0.2,
                 noise: float = 1e-4, xi: float = 0.01,
                 seed: int | None = None):
        self.space = space
        self.metric = metric
        self.mode = mode
        self.budget = num_samples
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.xi = xi
        self.rng = random.Random(seed)
        self._suggested = 0
        self._configs: dict[str, dict] = {}
        self._obs: dict[str, tuple[dict, float]] = {}
        # numeric dimensions the GP models (bounded Domains)
        self._dims = [k for k, v in space.items()
                      if isinstance(v, Domain) and v.low is not None
                      and v.high is not None]

    def on_trial_complete(self, trial_id, result=None, error=False):
        if error or not result or self.metric not in result:
            return
        cfg = self._configs.get(trial_id)
        if cfg is not None:
            self._obs[trial_id] = (cfg, float(result[self.metric]))

    def _unit(self, cfg: dict):
        """Config -> [0,1]^d vector over the modeled dims (log-scale is
        approximated linearly; adequate for acquisition ranking)."""
        import numpy as np

        x = np.empty(len(self._dims))
        for i, k in enumerate(self._dims):
            dom = self.space[k]
            x[i] = (float(cfg[k]) - dom.low) / (dom.high - dom.low)
        return x

    def _random_config(self) -> dict:
        return {k: (v.sample(self.rng) if isinstance(v, Domain) else v)
                for k, v in self.space.items()}

    def suggest(self, trial_id: str) -> dict | None:
        if self._suggested >= self.budget:
            return None
        self._suggested += 1
        if len(self._obs) < self.n_startup or not self._dims:
            cfg = self._random_config()
        else:
            cfg = self._gp_config()
        self._configs[trial_id] = cfg
        return cfg

    def _gp_config(self) -> dict:
        import numpy as np

        obs = list(self._obs.values())
        X = np.stack([self._unit(c) for c, _ in obs])
        y = np.array([s for _, s in obs])
        if self.mode == "min":
            y = -y
        y_mean, y_std = y.mean(), y.std() + 1e-9
        yn = (y - y_mean) / y_std

        def kern(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / self.length_scale ** 2)

        K = kern(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

        # candidates: prior samples + jittered best observed
        cands = [self._random_config()
                 for _ in range(self.n_candidates // 2)]
        best_cfg = obs[int(np.argmax(yn))][0]
        for _ in range(self.n_candidates - len(cands)):
            c = dict(self._random_config())
            for k in self._dims:
                dom = self.space[k]
                span = (dom.high - dom.low) * 0.1
                c[k] = dom.clamp(float(best_cfg[k])
                                 + self.rng.gauss(0.0, span))
            cands.append(c)
        Xc = np.stack([self._unit(c) for c in cands])
        Kc = kern(Xc, X)
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.maximum(1.0 - (v ** 2).sum(0), 1e-12)
        sigma = np.sqrt(var)
        # expected improvement over the incumbent
        from math import erf

        best = yn.max()
        z = (mu - best - self.xi) / sigma
        cdf = 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2.0)))
        pdf = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
        ei = (mu - best - self.xi) * cdf + sigma * pdf
        pick = cands[int(np.argmax(ei))]
        # re-clamp integer dims disturbed by jitter
        return {k: (self.space[k].clamp(v)
                    if k in self._dims and isinstance(self.space[k], Domain)
                    else v)
                for k, v in pick.items()}


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference:
    tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> dict | None:
        if len(self._live) >= self.max_concurrent:
            return None  # controller retries later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

