"""Search spaces + searchers.

Reference analog: ``python/ray/tune/search/`` — the sampling primitives
(``tune.uniform/choice/...``), ``grid_search``, and
``BasicVariantGenerator`` (grid expansion × num_samples random sampling).
External searcher integrations (optuna/hyperopt/...) plug in behind the
same ``suggest`` interface."""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]

    def sample(self, rng: random.Random):
        return self.sampler(rng)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Domain:
    import math

    return Domain(lambda rng: math.exp(
        rng.uniform(math.log(low), math.log(high))))


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high))


def choice(options: list) -> Domain:
    options = list(options)
    return Domain(lambda rng: rng.choice(options))


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


class BasicVariantGenerator:
    """Expands grid_search axes (cartesian product) and samples Domains;
    ``num_samples`` repeats the whole expansion (reference semantics)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> list[dict]:
        grid_keys = []
        grid_values = []

        def walk(prefix, node):
            for k, v in node.items():
                path = prefix + (k,)
                if isinstance(v, dict) and "grid_search" in v:
                    grid_keys.append(path)
                    grid_values.append(v["grid_search"])
                elif isinstance(v, dict):
                    walk(path, v)

        walk((), self.param_space)
        combos = list(itertools.product(*grid_values)) if grid_values else [()]
        out = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg = self._sample(self.param_space)
                for path, value in zip(grid_keys, combo):
                    _set_path(cfg, path, value)
                out.append(cfg)
        return out

    def _sample(self, node: dict) -> dict:
        cfg = {}
        for k, v in node.items():
            if isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            elif isinstance(v, dict) and "grid_search" in v:
                cfg[k] = None  # filled by the grid combo
            elif isinstance(v, dict):
                cfg[k] = self._sample(v)
            else:
                cfg[k] = v
        return cfg


def _set_path(cfg: dict, path: tuple, value):
    node = cfg
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value
