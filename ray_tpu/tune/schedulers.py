"""Trial schedulers: early stopping + population-based training.

Reference analog: ``python/ray/tune/schedulers/`` —
``AsyncHyperBandScheduler`` (async_hyperband.py:19, ASHA rung-based
promotion/halting), ``MedianStoppingRule``, and ``PopulationBasedTraining``
(pbt.py:222, exploit bottom quantile from top quantile + perturb)."""

from __future__ import annotations

import math
import random
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial, result: dict) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA: rungs at grace_period * reduction_factor^k; a trial reaching a
    rung halts unless its metric is in the top 1/reduction_factor of
    completions at that rung."""

    def __init__(self, *, metric: str, mode: str = "max",
                 grace_period: int = 1, max_t: int = 100,
                 reduction_factor: int = 3, time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.rungs: list[tuple[int, list]] = []
        t = grace_period
        while t < max_t:
            self.rungs.append((t, []))
            t *= reduction_factor
        self.rf = reduction_factor

    def _val(self, result):
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        for rung_t, recorded in self.rungs:
            if t == rung_t:
                value = self._val(result)
                recorded.append(value)
                k = max(1, len(recorded) // self.rf)
                threshold = sorted(recorded, reverse=True)[k - 1]
                if value < threshold:
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose best metric is below the median of running means
    of completed/ongoing trials at the same step."""

    def __init__(self, *, metric: str, mode: str = "max",
                 grace_period: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self._means: dict[Any, tuple[float, int]] = {}

    def _val(self, result):
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: dict) -> str:
        value = self._val(result)
        total, n = self._means.get(trial.trial_id, (0.0, 0))
        self._means[trial.trial_id] = (total + value, n + 1)
        t = int(result.get(self.time_attr, 0))
        if t < self.grace or len(self._means) < 3:
            return CONTINUE
        means = [s / max(1, c) for s, c in self._means.values()]
        means.sort()
        median = means[len(means) // 2]
        my_total, my_n = self._means[trial.trial_id]
        if my_total / my_n < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining:
    """PBT: every perturbation_interval, bottom-quantile trials exploit a
    top-quantile donor (copy config + checkpoint) and explore (perturb
    hyperparams). The controller applies the returned action."""

    def __init__(self, *, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25, seed: int | None = None,
                 time_attr: str = "training_iteration",
                 max_exploits_per_trial: int = 8):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._latest: dict[Any, float] = {}
        # exploit budget per trial: a population ALWAYS has a bottom
        # quantile, so without a cap a rerun-from-scratch function
        # trainable can be exploited forever and the experiment never
        # terminates (the reference bounds runs via stop criteria on a
        # cumulative iteration count that restarts don't reset)
        self.max_exploits = max_exploits_per_trial
        self._exploits: dict[Any, int] = {}

    def _val(self, result):
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: dict) -> str:
        self._latest[trial.trial_id] = self._val(result)
        t = int(result.get(self.time_attr, 0))
        if t == 0 or t % self.interval or len(self._latest) < 4:
            return CONTINUE
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        k = max(1, int(len(ranked) * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if (trial.trial_id in bottom
                and self._exploits.get(trial.trial_id, 0)
                < self.max_exploits):
            self._exploits[trial.trial_id] = 1 + self._exploits.get(
                trial.trial_id, 0)
            donor = self.rng.choice(top)
            return ("EXPLOIT", donor)
        return CONTINUE

    def explore(self, config: dict) -> dict:
        out = dict(config)
        for key, mutation in self.mutations.items():
            if callable(mutation):
                out[key] = mutation()
            elif isinstance(mutation, list):
                out[key] = self.rng.choice(mutation)
            elif key in out and isinstance(out[key], (int, float)):
                factor = self.rng.choice([0.8, 1.2])
                out[key] = out[key] * factor
        return out


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: ``tune/schedulers/pb2.py``):
    PBT's exploit step, but explore proposes hyperparameters with a
    GP-UCB bandit fit to observed (config -> score-improvement) data
    instead of random perturbation — far more sample-efficient for small
    populations. ``hyperparam_bounds`` maps each tuned key to
    ``(low, high)``; proposals are drawn from the bounds and scored by a
    tiny RBF-kernel Gaussian process over past observations.
    """

    def __init__(self, *, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25, seed: int | None = None,
                 time_attr: str = "training_iteration",
                 ucb_beta: float = 1.0, n_candidates: int = 32):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed,
                         time_attr=time_attr)
        self.bounds = hyperparam_bounds or {}
        self.ucb_beta = ucb_beta
        self.n_candidates = n_candidates
        # observations: (normalized config vector, score delta)
        self._obs: list[tuple[list, float]] = []
        self._prev_score: dict[Any, float] = {}

    def _norm(self, config: dict) -> list:
        vec = []
        for key, (lo, hi) in self.bounds.items():
            x = float(config.get(key, lo))
            vec.append((x - lo) / max(hi - lo, 1e-12))
        return vec

    def on_result(self, trial, result: dict) -> str:
        score = self._val(result)
        prev = self._prev_score.get(trial.trial_id)
        cfg = getattr(trial, "config", None) or {}
        if prev is not None and self.bounds:
            self._obs.append((self._norm(cfg), score - prev))
            if len(self._obs) > 256:
                self._obs.pop(0)
        self._prev_score[trial.trial_id] = score
        decision = super().on_result(trial, result)
        if isinstance(decision, tuple) and decision[0] == "EXPLOIT":
            # the trial restarts from the donor's checkpoint with a new
            # config: its next score delta is the checkpoint copy, not
            # the config — break the continuity so it isn't recorded
            self._prev_score.pop(trial.trial_id, None)
        return decision

    def _gp_ucb(self, x: list) -> float:
        """Posterior mean + beta * sd under an RBF-kernel GP with unit
        prior and fixed noise (the PB2 paper's time-varying bandit,
        simplified to a stationary kernel over the recent window)."""
        import math

        if not self._obs:
            return 0.0
        ls, noise = 0.3, 0.1
        xs = [o[0] for o in self._obs]
        ys = [o[1] for o in self._obs]
        # kernel-weighted mean/uncertainty (Nadaraya-Watson approximation
        # of the posterior: exact GP inversion is overkill at this size)
        ws = [math.exp(-sum((a - b) ** 2 for a, b in zip(x, xi))
                       / (2 * ls * ls)) for xi in xs]
        wsum = sum(ws) + noise
        mean = sum(w * y for w, y in zip(ws, ys)) / wsum
        sd = 1.0 / math.sqrt(wsum)
        return mean + self.ucb_beta * sd

    def explore(self, config: dict) -> dict:
        if not self.bounds:
            return super().explore(config)
        best, best_score = None, -float("inf")
        for _ in range(self.n_candidates):
            cand = dict(config)
            for key, (lo, hi) in self.bounds.items():
                cand[key] = lo + self.rng.random() * (hi - lo)
            s = self._gp_ucb(self._norm(cand))
            if s > best_score:
                best, best_score = cand, s
        return best


class HyperBandScheduler:
    """Synchronous HyperBand-style successive halving (reference:
    ``tune/schedulers/hyperband.py``). Simplification: one bracket sized
    by the live trial population; at each rung boundary (``r * eta^k``
    iterations) the bottom ``1 - 1/eta`` of trials AT that rung stop.

    Unlike ASHA (async, per-result decisions vs historical quantiles),
    rung cuts here wait until every live trial reaches the rung, which
    matches the original algorithm's synchronous halving semantics."""

    def __init__(self, *, metric: str, mode: str = "max", r: int = 1,
                 eta: int = 3, max_t: int = 81,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.eta = eta
        self.max_t = max_t
        self.time_attr = time_attr
        self.rungs = []
        t = r
        while t < max_t:
            self.rungs.append(t)
            t *= eta
        # rung level -> {trial_id: score} of trials waiting at the rung
        self._waiting: dict[int, dict] = {lvl: {} for lvl in self.rungs}
        self._decided: dict[int, set] = {lvl: set() for lvl in self.rungs}
        self._stopped: set = set()
        # expected population: set by the controller (set_population) so a
        # rung cut waits for EVERY planned trial, not just the subset that
        # happens to have reported already (a singleton cut eliminates
        # nobody and silently defeats successive halving)
        self._population: set = set()

    def _val(self, result):
        return float(result[self.metric]) * (
            1.0 if self.mode == "max" else -1.0)

    def set_population(self, trial_ids):
        """Controller hook: the full set of trials this bracket halves
        over (called whenever trials are created)."""
        self._population.update(trial_ids)

    def on_result(self, trial, result: dict) -> str:
        self._population.add(trial.trial_id)
        if trial.trial_id in self._stopped:
            return STOP
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        for lvl in self.rungs:
            if t == lvl and trial.trial_id not in self._decided[lvl]:
                self._waiting[lvl][trial.trial_id] = self._val(result)
                undecided = (self._population - self._decided[lvl]
                             - self._stopped)
                if set(self._waiting[lvl]) >= undecided:
                    # everyone still running has reached the rung: cut
                    ranked = sorted(self._waiting[lvl].items(),
                                    key=lambda kv: kv[1], reverse=True)
                    keep = max(1, len(ranked) // self.eta)
                    for tid, _ in ranked[keep:]:
                        self._stopped.add(tid)
                    for tid, _ in ranked:
                        self._decided[lvl].add(tid)
                    self._waiting[lvl].clear()
                    if trial.trial_id in self._stopped:
                        return STOP
                # NOT decided yet: let the trial keep running; it will
                # be stopped at its next report if the cut rejects it
        return CONTINUE

    def on_trial_gone(self, trial_id: str):
        """A trial finished/errored outside scheduler control: it must
        not hold up future rung cuts, and its stale score must not take
        a keep slot from live trials."""
        self._population.discard(trial_id)
        for lvl in self.rungs:
            self._waiting[lvl].pop(trial_id, None)
