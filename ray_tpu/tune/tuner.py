"""Tuner + trial-driving controller.

Reference analog: ``python/ray/tune/tuner.py`` (``Tuner:59``) and
``tune/execution/tune_controller.py`` (``TuneController:80`` — the event
loop owning trial actors through the AIR actor manager). Here each trial
is one rank-actor group (``BackendExecutor`` with 1 worker unless the
trainable is itself a DataParallelTrainer config); the controller polls
report buses, applies scheduler decisions (ASHA halting, PBT exploit), and
persists experiment state for ``Tuner.restore``-style resume.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import BackendExecutor
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import BasicVariantGenerator


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    seed: int | None = None
    time_attr: str = "training_iteration"


@dataclass
class Trial:
    trial_id: str
    config: dict
    status: str = "PENDING"   # PENDING | RUNNING | TERMINATED | STOPPED | ERROR
    last_result: dict = field(default_factory=dict)
    results: list = field(default_factory=list)
    iteration: int = 0
    executor: Any = None
    error: str | None = None
    checkpoint_dir: str | None = None


@dataclass
class ResultGrid:
    trials: list[Trial]

    def get_best_result(self, metric: str, mode: str = "max") -> Trial:
        scored = [t for t in self.trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda t: float(t.last_result[metric])  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def __iter__(self):
        return iter(self.trials)

    def __len__(self):
        return len(self.trials)


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        variants = BasicVariantGenerator(
            self.param_space, num_samples=self.tune_config.num_samples,
            seed=self.tune_config.seed).variants()
        trials = [Trial(trial_id=f"trial_{i:05d}", config=cfg)
                  for i, cfg in enumerate(variants)]
        controller = TuneController(
            self.trainable, trials, self.tune_config, self.run_config)
        controller.run()
        return ResultGrid(trials)


class TuneController:
    """Event loop: start trials up to the concurrency cap, drain reports,
    ask the scheduler about each result, stop/exploit accordingly."""

    def __init__(self, trainable, trials, tune_config: TuneConfig,
                 run_config: RunConfig):
        self.trainable = trainable
        self.trials = trials
        self.cfg = tune_config
        self.run_config = run_config
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.exp_dir = run_config.resolved_storage_path()
        os.makedirs(self.exp_dir, exist_ok=True)

    # -- trial lifecycle -------------------------------------------------
    def _start(self, trial: Trial):
        trial.executor = BackendExecutor(ScalingConfig(num_workers=1))
        trial_dir = os.path.join(self.exp_dir, trial.trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        trial.executor.start_training(self.trainable, dict(trial.config),
                                      trial_dir)
        trial.status = "RUNNING"

    def _stop(self, trial: Trial, status: str):
        if trial.executor is not None:
            trial.executor.shutdown()
            trial.executor = None
        trial.status = status

    def _exploit(self, trial: Trial, donor: Trial):
        """PBT exploit: adopt donor's (explored) config + checkpoint and
        restart (reference: pbt.py _exploit)."""
        explored = self.scheduler.explore(dict(donor.config))
        self._stop(trial, "PENDING")
        trial.config = explored
        trial.checkpoint_dir = donor.checkpoint_dir
        trial.iteration = 0

    # -- event loop ------------------------------------------------------
    def run(self):
        pending = list(self.trials)
        running: list[Trial] = []
        while pending or running:
            while pending and len(running) < self.cfg.max_concurrent_trials:
                trial = pending.pop(0)
                self._start(trial)
                running.append(trial)
            time.sleep(0.02)
            for trial in list(running):
                reports, done = trial.executor.poll_reports()
                for rep in reports:
                    if "error" in rep:
                        trial.error = rep["error"]
                        continue
                    trial.iteration += 1
                    result = dict(rep["metrics"])
                    result.setdefault(self.cfg.time_attr, trial.iteration)
                    trial.last_result = result
                    trial.results.append(result)
                    if rep.get("checkpoint"):
                        trial.checkpoint_dir = rep["checkpoint"]
                    decision = self.scheduler.on_result(trial, result)
                    if decision == STOP:
                        self._stop(trial, "STOPPED")
                        running.remove(trial)
                        break
                    if isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                        donor = next((t for t in self.trials
                                      if t.trial_id == decision[1]), None)
                        if donor is not None and donor is not trial:
                            self._exploit(trial, donor)
                            running.remove(trial)
                            pending.append(trial)
                            break
                else:
                    if done:
                        self._stop(trial,
                                   "ERROR" if trial.error else "TERMINATED")
                        running.remove(trial)
            self._save_state()
        self._save_state()

    def _save_state(self):
        state = [{"trial_id": t.trial_id, "status": t.status,
                  "config": _jsonable(t.config),
                  "last_result": _jsonable(t.last_result),
                  "checkpoint_dir": t.checkpoint_dir}
                 for t in self.trials]
        with open(os.path.join(self.exp_dir, "experiment_state.json"),
                  "w") as f:
            json.dump(state, f)


def _jsonable(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = repr(v)
    return out
