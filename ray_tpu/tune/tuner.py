"""Tuner + trial-driving controller.

Reference analog: ``python/ray/tune/tuner.py`` (``Tuner:59``) and
``tune/execution/tune_controller.py`` (``TuneController:80`` — the event
loop owning trial actors through the AIR actor manager). Here each trial
is one rank-actor group (``BackendExecutor`` with 1 worker unless the
trainable is itself a DataParallelTrainer config); the controller polls
report buses, applies scheduler decisions (ASHA halting, PBT exploit), and
persists experiment state for ``Tuner.restore``-style resume.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import BackendExecutor
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import BasicVariantGenerator


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    # adaptive searcher (Searcher: suggest/on_trial_complete); when set,
    # trials are created on demand from its suggestions instead of
    # pre-expanding the param space (reference: search_alg in TuneConfig)
    search_alg: Any = None
    seed: int | None = None
    time_attr: str = "training_iteration"
    # Callback objects with optional on_trial_start/on_trial_result/
    # on_trial_complete hooks (reference: tune/callback.py)
    callbacks: list = field(default_factory=list)


@dataclass
class Trial:
    trial_id: str
    config: dict
    status: str = "PENDING"   # PENDING | RUNNING | TERMINATED | STOPPED | ERROR
    last_result: dict = field(default_factory=dict)
    results: list = field(default_factory=list)
    iteration: int = 0
    executor: Any = None
    error: str | None = None
    checkpoint_dir: str | None = None
    # resume this trial from checkpoint_dir when (re)started
    restore_from_checkpoint: bool = False


@dataclass
class ResultGrid:
    trials: list[Trial]

    def get_best_result(self, metric: str, mode: str = "max") -> Trial:
        scored = [t for t in self.trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda t: float(t.last_result[metric])  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def __iter__(self):
        return iter(self.trials)

    def __len__(self):
        return len(self.trials)


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        if self._restored_trials is not None:
            # Tuner.restore(...).fit() continues the experiment —
            # mirrors the reference pairing; fit_restored stays as an
            # explicit alias
            return self.fit_restored()
        if self.tune_config.search_alg is not None:
            trials: list[Trial] = []  # created on demand by the controller
        else:
            variants = BasicVariantGenerator(
                self.param_space, num_samples=self.tune_config.num_samples,
                seed=self.tune_config.seed).variants()
            trials = [Trial(trial_id=f"trial_{i:05d}", config=cfg)
                      for i, cfg in enumerate(variants)]
        controller = TuneController(
            self.trainable, trials, self.tune_config, self.run_config)
        controller.run()
        return ResultGrid(controller.trials)

    @classmethod
    def restore(cls, path: str, trainable: Callable, *,
                tune_config: TuneConfig | None = None) -> "Tuner":
        """Resume an interrupted experiment from its state file
        (reference: ``Tuner.restore`` + ``tune/execution/
        experiment_state.py``). Finished trials keep their results;
        unfinished ones re-run, resuming from their last checkpoint."""
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=RunConfig(storage_path=path))
        restored = []
        for t in state:
            trial = Trial(trial_id=t["trial_id"], config=t["config"],
                          status=t["status"],
                          last_result=t.get("last_result") or {},
                          checkpoint_dir=t.get("checkpoint_dir"))
            if trial.status in ("PENDING", "RUNNING", "ERROR"):
                trial.status = "PENDING"
                trial.restore_from_checkpoint = True
            restored.append(trial)
        tuner._restored_trials = restored
        return tuner

    _restored_trials: list | None = None

    def fit_restored(self) -> ResultGrid:
        """Continue a restored experiment (fit() for Tuner.restore)."""
        assert self._restored_trials is not None, "use Tuner.restore first"
        controller = TuneController(
            self.trainable, self._restored_trials, self.tune_config,
            self.run_config)
        controller.run(only_pending=True)
        return ResultGrid(controller.trials)


class TuneController:
    """Event loop: start trials up to the concurrency cap, drain reports,
    ask the scheduler about each result, stop/exploit accordingly."""

    def __init__(self, trainable, trials, tune_config: TuneConfig,
                 run_config: RunConfig):
        self.trainable = trainable
        self.trials = trials
        self.cfg = tune_config
        self.run_config = run_config
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.exp_dir = run_config.resolved_storage_path()
        os.makedirs(self.exp_dir, exist_ok=True)

    # -- trial lifecycle -------------------------------------------------
    def _start(self, trial: Trial):
        trial.executor = BackendExecutor(ScalingConfig(num_workers=1))
        trial_dir = os.path.join(self.exp_dir, trial.trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        restore = (trial.checkpoint_dir
                   if trial.restore_from_checkpoint else None)
        trial.restore_from_checkpoint = False
        trial.executor.start_training(self.trainable, dict(trial.config),
                                      trial_dir,
                                      restore_checkpoint=restore)
        trial.status = "RUNNING"
        self._callback("on_trial_start", trial)

    def _callback(self, hook: str, trial: Trial, result: dict | None = None):
        for cb in self.cfg.callbacks:
            fn = getattr(cb, hook, None)
            if fn is not None:
                fn(trial, result) if result is not None else fn(trial)

    def _stop(self, trial: Trial, status: str):
        if trial.executor is not None:
            trial.executor.shutdown()
            trial.executor = None
        trial.status = status

    def _exploit(self, trial: Trial, donor: Trial):
        """PBT exploit: adopt donor's (explored) config + checkpoint and
        restart from it (reference: pbt.py _exploit)."""
        explored = self.scheduler.explore(dict(donor.config))
        self._stop(trial, "PENDING")
        trial.config = explored
        trial.checkpoint_dir = donor.checkpoint_dir
        trial.restore_from_checkpoint = donor.checkpoint_dir is not None
        trial.iteration = 0

    # -- event loop ------------------------------------------------------
    def run(self, only_pending: bool = False):
        pending = [t for t in self.trials
                   if not only_pending or t.status == "PENDING"]
        running: list[Trial] = []
        search = self.cfg.search_alg
        next_id = len(self.trials)
        while pending or running or search is not None:
            # adaptive search: pull new suggestions up to the cap
            while (search is not None
                   and len(running) + len(pending)
                   < self.cfg.max_concurrent_trials):
                tid = f"trial_{next_id:05d}"
                cfg = search.suggest(tid)
                if cfg is None:
                    if not running and not pending:
                        search = None  # budget exhausted: drain and exit
                    break
                next_id += 1
                t = Trial(trial_id=tid, config=cfg)
                self.trials.append(t)
                pending.append(t)
            if search is None and not pending and not running:
                break
            while pending and len(running) < self.cfg.max_concurrent_trials:
                trial = pending.pop(0)
                self._start(trial)
                running.append(trial)
            set_pop = getattr(self.scheduler, "set_population", None)
            if set_pop is not None:
                set_pop({t.trial_id for t in self.trials
                         if t.status in ("PENDING", "RUNNING")}
                        | {t.trial_id for t in running})
            time.sleep(0.02)
            # Drain every running trial's reports, then process them
            # ROUND-ROBIN one report at a time. Per-trial batch
            # processing would let a fast trial replay its whole history
            # before a sibling's first report is seen, which collapses
            # population-based scheduler decisions (HyperBand rungs, PBT
            # quantiles) to single-trial populations.
            drained: dict = {}
            for trial in list(running):
                reports, done = trial.executor.poll_reports()
                drained[trial.trial_id] = [list(reports), done]
            progressed = True
            while progressed:
                progressed = False
                for trial in list(running):
                    slot = drained.get(trial.trial_id)
                    if not slot or not slot[0]:
                        continue
                    rep = slot[0].pop(0)
                    progressed = True
                    if "error" in rep:
                        trial.error = rep["error"]
                        continue
                    trial.iteration += 1
                    result = dict(rep["metrics"])
                    result.setdefault(self.cfg.time_attr, trial.iteration)
                    trial.last_result = result
                    trial.results.append(result)
                    if rep.get("checkpoint"):
                        trial.checkpoint_dir = rep["checkpoint"]
                    if self.cfg.search_alg is not None:
                        self.cfg.search_alg.on_trial_result(
                            trial.trial_id, result)
                    self._callback("on_trial_result", trial, result)
                    decision = self.scheduler.on_result(trial, result)
                    if decision == STOP:
                        self._stop(trial, "STOPPED")
                        running.remove(trial)
                        drained.pop(trial.trial_id, None)
                        self._trial_over(trial)
                    elif (isinstance(decision, tuple)
                          and decision[0] == "EXPLOIT"):
                        donor = next((t for t in self.trials
                                      if t.trial_id == decision[1]), None)
                        if donor is not None and donor is not trial:
                            self._exploit(trial, donor)
                            running.remove(trial)
                            drained.pop(trial.trial_id, None)
                            pending.append(trial)
            for trial in list(running):
                slot = drained.get(trial.trial_id)
                if slot and slot[1]:  # done and all reports consumed
                    self._stop(trial,
                               "ERROR" if trial.error else "TERMINATED")
                    running.remove(trial)
                    self._trial_over(trial)
            self._save_state()
        self._save_state()

    def _trial_over(self, trial: Trial):
        if self.cfg.search_alg is not None:
            self.cfg.search_alg.on_trial_complete(
                trial.trial_id, trial.last_result or None,
                error=trial.status == "ERROR")
        gone = getattr(self.scheduler, "on_trial_gone", None)
        if gone is not None:
            gone(trial.trial_id)
        self._callback("on_trial_complete", trial)

    def _save_state(self):
        state = [{"trial_id": t.trial_id, "status": t.status,
                  "config": _jsonable(t.config),
                  "last_result": _jsonable(t.last_result),
                  "checkpoint_dir": t.checkpoint_dir}
                 for t in self.trials]
        with open(os.path.join(self.exp_dir, "experiment_state.json"),
                  "w") as f:
            json.dump(state, f)


def _jsonable(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = repr(v)
    return out
