"""Client server: hosts the real driver runtime for remote clients.

Reference analog: ``python/ray/util/client/server/`` (proxier + server
speaking ray_client.proto). One server process serves many clients over
the shared RpcServer transport; it either runs a local in-process
runtime or attaches to a cluster (GCS address), and all object ownership
lives here.

Run standalone:
    python -m ray_tpu.client.server --port 10001 [--address GCS_HOST:PORT]
Then from anywhere:
    ray_tpu.init(address="client://HOST:10001")
"""

from __future__ import annotations

import threading
import time
import uuid

import cloudpickle

from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.rpc import RpcServer
from ray_tpu.runtime.task_spec import ResourceSet, TaskSpec, TaskType
from ray_tpu.utils.ids import ActorID, ObjectID, TaskID


def _unwire_args(blob: bytes):
    args, kwargs = cloudpickle.loads(blob)
    args = [ObjectRef(ObjectID.from_hex(a[1]))
            if isinstance(a, tuple) and len(a) == 2 and a[0] == "__objref__"
            else a for a in args]
    kwargs = {k: ObjectRef(ObjectID.from_hex(v[1]))
              if isinstance(v, tuple) and len(v) == 2 and v[0] == "__objref__"
              else v for k, v in kwargs.items()}
    return args, kwargs


class ClientServer(RpcServer):
    """Serves client_* RPCs against an owned driver runtime."""

    def __init__(self, host: str = "127.0.0.1", port: int = 10001, *,
                 gcs_address=None, num_cpus: float | None = None,
                 exit_when_idle_s: float | None = None):
        super().__init__(host, port)
        import ray_tpu

        # proxied per-job servers exit when their last session reaps
        # (reference: the proxier's SpecificServer lifetime follows its
        # client job). Armed from PROCESS START: a child whose client
        # dies before ever establishing a session must also expire, or
        # every failed hello leaks a full driver-runtime process.
        self._exit_when_idle_s = exit_when_idle_s
        self._idle_since: float | None = (
            time.monotonic() if exit_when_idle_s is not None else None)

        if gcs_address is not None:
            self._rt = ray_tpu.init(address=gcs_address)
        else:
            self._rt = ray_tpu.init(
                num_cpus=num_cpus if num_cpus is not None else 4,
                num_tpus=0)
        # Ownership lives HERE (reference: the client server owns client
        # objects — util/client/server/): remote clients hold no process-
        # local ObjectRefs, so the server retains one per client-visible
        # object or distributed refcounting would free them the moment
        # the transient RPC-scope ref dropped. State is scoped per
        # SESSION (a client-chosen token), not per connection: a dropped
        # TCP connection keeps the session alive for a reconnect grace
        # window (reference: client proxier 30s reconnect grace), then
        # the session's objects are released and its non-detached actors
        # killed — the per-client-driver lifetime the reference gets
        # from one ray instance per proxied client.
        from ray_tpu.utils.config import get_config
        self._grace = get_config().client_reconnect_grace_s
        self._slock = threading.Lock()
        self._sessions: dict[str, dict] = {}
        self._conn_session: dict[int, str] = {}
        self._reaper = threading.Thread(target=self._reap_loop,
                                        daemon=True, name="client-reaper")
        self._reaper.start()

    def _session_for(self, conn) -> dict:
        with self._slock:
            token = self._conn_session.get(id(conn))
            if token is None:
                # hello-less legacy client: one implicit session per conn
                token = f"conn-{id(conn)}"
                self._conn_session[id(conn)] = token
            sess = self._sessions.get(token)
            if sess is None:
                sess = self._new_session_locked(token)
            sess["conns"].add(id(conn))
            return sess

    def _new_session_locked(self, token: str) -> dict:
        sess = {"token": token, "held": {}, "actors": set(),
                "conns": set(), "reap_at": None}
        self._sessions[token] = sess
        return sess

    def _retain(self, conn, refs):
        table = self._session_for(conn)["held"]
        for r in refs:
            table.setdefault(r.hex(), r)

    def on_disconnect(self, conn):
        with self._slock:
            token = self._conn_session.pop(id(conn), None)
            sess = self._sessions.get(token) if token else None
            if sess is None:
                return
            sess["conns"].discard(id(conn))
            if not sess["conns"]:
                # grace window: a reconnecting client re-hellos with its
                # token and cancels the reap
                sess["reap_at"] = time.monotonic() + self._grace

    def _reap_loop(self):
        import os

        while not self._stopping:
            time.sleep(0.25)
            now = time.monotonic()
            doomed = []
            with self._slock:
                for token, sess in list(self._sessions.items()):
                    at = sess["reap_at"]
                    if at is not None and now >= at and not sess["conns"]:
                        doomed.append(self._sessions.pop(token))
                if self._sessions:
                    self._idle_since = None
                elif self._idle_since is None:
                    self._idle_since = now
            for sess in doomed:
                self._reap_session(sess)
            if (self._exit_when_idle_s is not None
                    and self._idle_since is not None
                    and now - self._idle_since >= self._exit_when_idle_s):
                # proxied per-job server: job over, process over
                os._exit(0)

    def _reap_session(self, sess: dict):
        """The session's objects die with it; its non-detached actors
        are killed (owner-scoped lifetime for remote-client drivers)."""
        sess["held"].clear()
        for actor_hex in sess["actors"]:
            try:
                self._rt.kill_actor(ActorID.from_hex(actor_hex),
                                    no_restart=True)
            except Exception:  # noqa: BLE001 - already dead is fine
                pass

    # -- session ---------------------------------------------------------

    def rpc_client_hello(self, conn, send_lock, *, session_token=None):
        token = session_token or uuid.uuid4().hex
        with self._slock:
            sess = self._sessions.get(token)
            resumed = sess is not None
            if sess is None:
                sess = self._new_session_locked(token)
            sess["conns"].add(id(conn))
            sess["reap_at"] = None          # reconnect cancels the reap
            self._conn_session[id(conn)] = token
        import os

        job = getattr(self._rt, "job_id", None)
        return {"job_id": job.hex() if job is not None else "cluster",
                "session_token": token, "resumed": resumed,
                "server_pid": os.getpid()}

    def rpc_client_disconnect(self, conn, send_lock):
        """Explicit goodbye: reap NOW, no grace."""
        with self._slock:
            token = self._conn_session.pop(id(conn), None)
            sess = self._sessions.pop(token, None) if token else None
        if sess is not None:
            self._reap_session(sess)
        return {"ok": True}

    # -- objects ---------------------------------------------------------

    def rpc_client_put(self, conn, send_lock, *, blob: bytes) -> str:
        ref = self._rt.put(cloudpickle.loads(blob))
        self._retain(conn, [ref])
        return ref.id.hex()

    def rpc_client_get(self, conn, send_lock, *, oids, get_timeout=None):
        refs = [ObjectRef(ObjectID.from_hex(h)) for h in oids]
        try:
            values = self._rt.get(refs, timeout=get_timeout)
        except BaseException as e:  # noqa: BLE001 - ship to the client
            return {"error_blob": cloudpickle.dumps(e, protocol=5),
                    "values_blob": None}
        return {"error_blob": None,
                "values_blob": cloudpickle.dumps(values, protocol=5)}

    def rpc_client_wait(self, conn, send_lock, *, oids, num_returns,
                        wait_timeout=None):
        refs = [ObjectRef(ObjectID.from_hex(h)) for h in oids]
        ready, not_ready = self._rt.wait(refs, num_returns=num_returns,
                                         timeout=wait_timeout)
        return {"ready": [r.id.hex() for r in ready],
                "not_ready": [r.id.hex() for r in not_ready]}

    def rpc_client_release(self, conn, send_lock, *, oids):
        """Incremental release: the client's local ObjectRefs for these
        oids were garbage collected (reference: the client's
        ReleaseObject calls) — drop the session holds; the server-side
        refs die with them and the cluster refcount protocol takes it
        from there."""
        table = self._session_for(conn)["held"]
        for o in oids:
            table.pop(o, None)
        return {"ok": True}

    def rpc_client_held_count(self, conn, send_lock):
        """Debug/observability: how many objects this session pins."""
        return {"held": len(self._session_for(conn)["held"])}

    def rpc_client_free(self, conn, send_lock, *, oids):
        with self._slock:
            tables = [s["held"] for s in self._sessions.values()]
        for table in tables:
            for o in oids:
                table.pop(o, None)
        self._rt.free([ObjectRef(ObjectID.from_hex(o)) for o in oids])
        return {"ok": True}

    def rpc_client_cancel(self, conn, send_lock, *, oid, force=False):
        self._rt.cancel(ObjectRef(ObjectID.from_hex(oid)), force=force)
        return {"ok": True}

    # -- tasks -----------------------------------------------------------

    def rpc_client_submit_task(self, conn, send_lock, *, name, fn_blob,
                               args_blob, num_returns, resources,
                               max_retries, retry_exceptions, runtime_env,
                               trace_ctx):
        args, kwargs = _unwire_args(args_blob)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.NORMAL_TASK,
            function=cloudpickle.loads(fn_blob),
            function_name=name,
            args=tuple(args),
            kwargs=kwargs,
            num_returns=num_returns,
            resources=ResourceSet({k: float(v)
                                   for k, v in (resources or {}).items()}),
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            runtime_env=runtime_env,
            trace_ctx=trace_ctx,
        )
        refs = self._rt.submit_task(spec)
        self._rt.note_return_owner(spec)
        self._retain(conn, refs)
        return [r.id.hex() for r in refs]

    def rpc_client_submit_actor_task(self, conn, send_lock, *, actor_id,
                                     method_name, name, args_blob,
                                     num_returns, trace_ctx):
        args, kwargs = _unwire_args(args_blob)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.ACTOR_TASK,
            function=None,
            function_name=name,
            args=tuple(args),
            kwargs=kwargs,
            num_returns=num_returns,
            actor_id=ActorID.from_hex(actor_id),
            actor_method_name=method_name,
            trace_ctx=trace_ctx,
        )
        refs = self._rt.submit_task(spec)
        self._rt.note_return_owner(spec)
        self._retain(conn, refs)
        return [r.id.hex() for r in refs]

    # -- actors ----------------------------------------------------------

    def rpc_client_create_actor(self, conn, send_lock, *, name, class_name,
                                cls_blob, args_blob, resources,
                                max_concurrency, max_restarts, runtime_env,
                                namespace=None, lifetime=None):
        args, kwargs = _unwire_args(args_blob)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.ACTOR_CREATION_TASK,
            function=cloudpickle.loads(cls_blob),
            function_name=class_name,
            args=tuple(args),
            kwargs=kwargs,
            num_returns=1,
            resources=ResourceSet({k: float(v)
                                   for k, v in (resources or {}).items()}),
            max_concurrency=max_concurrency,
            max_restarts=max_restarts,
            runtime_env=runtime_env,
        )
        try:
            actor_id = self._rt.create_actor(spec, name=name,
                                             namespace=namespace,
                                             lifetime=lifetime)
        except ValueError as e:
            return {"error": str(e), "actor_id": None}
        if lifetime != "detached":
            # session-scoped lifetime: reaped with the client session
            self._session_for(conn)["actors"].add(actor_id.hex())
        return {"error": None, "actor_id": actor_id.hex()}

    def rpc_client_kill_actor(self, conn, send_lock, *, actor_id,
                              no_restart):
        self._rt.kill_actor(ActorID.from_hex(actor_id),
                            no_restart=no_restart)
        return {"ok": True}

    def rpc_client_get_actor(self, conn, send_lock, *, name,
                             namespace=None):
        try:
            actor_id = self._rt.get_actor(name, namespace) if namespace \
                else self._rt.get_actor(name)
        except ValueError as e:
            return {"error": str(e), "actor_id": None}
        return {"error": None, "actor_id": actor_id.hex()}

    # -- introspection ----------------------------------------------------

    def rpc_client_cluster_resources(self, conn, send_lock):
        return {"total": self._rt.cluster_resources(),
                "available": self._rt.available_resources_snapshot()}

    def rpc_client_task_events(self, conn, send_lock, *, limit=1000):
        if hasattr(self._rt, "task_events"):
            return self._rt.task_events(limit)
        return []

    def rpc_client_kv(self, conn, send_lock, *, op, key, value=None,
                      overwrite=True, prefix=""):
        """Proxy internal-KV ops so client drivers share the cluster's
        KV (not a process-local dict)."""
        from ray_tpu.experimental import internal_kv

        if op == "put":
            return internal_kv.internal_kv_put(key, value, overwrite)
        if op == "get":
            return internal_kv.internal_kv_get(key)
        if op == "del":
            return internal_kv.internal_kv_del(key)
        if op == "list":
            return internal_kv.internal_kv_list(prefix)
        raise ValueError(f"unknown kv op {op!r}")


def main(argv=None):
    import argparse
    import time

    parser = argparse.ArgumentParser(
        prog="ray-tpu-client-server",
        description="remote-driver server (ray:// analog)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--address", help="GCS host:port to attach to")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--exit-when-idle", type=float, default=None,
                        help="exit after this many seconds with no live "
                             "sessions (per-job proxied servers)")
    args = parser.parse_args(argv)

    gcs = None
    if args.address:
        host, _, port = args.address.rpartition(":")
        gcs = (host or "127.0.0.1", int(port))
    server = ClientServer(args.host, args.port, gcs_address=gcs,
                          num_cpus=args.num_cpus,
                          exit_when_idle_s=args.exit_when_idle).start()
    print(f"client server on {server.address[0]}:{server.address[1]}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
