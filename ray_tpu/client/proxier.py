"""Per-job client proxier: one dedicated server PROCESS per client job.

Reference analog: ``python/ray/util/client/server/proxier.py:113``
(``ProxyManager``) — the public ``ray://`` endpoint doesn't host client
state itself; it spawns a ``SpecificServer`` process per client job and
routes the client there, so one job's driver state (objects, actors,
crashes) is process-isolated from every other job's.

Here the public endpoint answers only ``client_hello``: it spawns (or
finds, for a reconnecting token) the session's own ``ClientServer``
subprocess and replies with a redirect; the client redials the child
directly — no per-request proxy hop (the reference proxies the gRPC
stream; a redirect is the cheaper equivalent for our framed-TCP
transport since the child is equally reachable). Children self-expire
via ``--exit-when-idle`` after their last session reaps.

Run standalone:
    python -m ray_tpu.client.proxier --port 10001 [--address GCS:PORT]
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
import uuid

from ray_tpu.runtime.rpc import RpcServer


class ProxyManager(RpcServer):
    """Public client endpoint that redirects each session to its own
    per-job server process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 10001, *,
                 gcs_address=None, num_cpus: float | None = None,
                 child_idle_exit_s: float = 60.0,
                 child_spawn_timeout_s: float = 60.0):
        super().__init__(host, port)
        self._host = host
        self._gcs = gcs_address
        self._num_cpus = num_cpus
        self._idle_exit = child_idle_exit_s
        self._spawn_timeout = child_spawn_timeout_s
        self._lock = threading.Lock()
        # token -> {"proc": Popen|None, "addr": (host, port)|None,
        #           "event": Event, "error": str|None}. addr None while
        # the spawn is in flight; waiters block on "event" OUTSIDE the
        # manager lock.
        self._children: dict[str, dict] = {}
        # test hook: command override for the per-job server child
        self._spawn_cmd: list[str] | None = None

    def _child_cmd(self) -> list[str]:
        if self._spawn_cmd is not None:
            return list(self._spawn_cmd)
        cmd = [sys.executable, "-m", "ray_tpu.client.server",
               "--host", self._host, "--port", "0",
               "--exit-when-idle", str(self._idle_exit)]
        if self._gcs is not None:
            cmd += ["--address", f"{self._gcs[0]}:{self._gcs[1]}"]
        if self._num_cpus is not None:
            cmd += ["--num-cpus", str(self._num_cpus)]
        return cmd

    def _spawn_child(self) -> dict:
        proc = subprocess.Popen(self._child_cmd(), stdout=subprocess.PIPE,
                                text=True)
        # First stdout line: "client server on HOST:PORT". The read runs
        # on a helper thread so the deadline is REAL — a child that
        # starts but never announces (wedged import, stolen stdout) used
        # to park this thread in readline() forever, the deadline only
        # checked between lines that never came.
        announced = threading.Event()
        state = {"line": ""}

        def _read_announce():
            for line in proc.stdout:
                if "client server on" in line:
                    state["line"] = line
                    announced.set()
                    break
            announced.set()   # EOF/exit with no announce: wake the waiter
            # keep draining so the child never blocks on a full pipe
            for _ in proc.stdout:
                pass

        threading.Thread(target=_read_announce, daemon=True,
                         name="proxier-announce-reader").start()
        if not announced.wait(timeout=self._spawn_timeout):
            proc.kill()
            raise RuntimeError(
                f"per-job client server did not announce within "
                f"{self._spawn_timeout}s")
        line = state["line"]
        if not line:
            rc = proc.poll()
            proc.kill()
            raise RuntimeError(
                f"per-job client server died at startup (rc={rc})")
        hostport = line.rsplit(" ", 1)[-1].strip()
        h, _, p = hostport.rpartition(":")
        if not p.isdigit():
            proc.kill()
            raise RuntimeError(
                f"per-job client server announced no address: {line!r}")
        return {"proc": proc, "addr": (h, int(p))}

    def rpc_client_hello(self, conn, send_lock, *, session_token=None):
        token = session_token or uuid.uuid4().hex
        spawn_needed = False
        with self._lock:
            child = self._children.get(token)
            if child is not None and child["proc"] is not None \
                    and child["proc"].poll() is not None:
                self._children.pop(token, None)
                child = None   # exited (idle or crash): respawn
            if child is None:
                # reap dead children while here (bounded table)
                for t, c in list(self._children.items()):
                    if c["proc"] is not None and c["proc"].poll() is not None:
                        self._children.pop(t)
                # publish a placeholder and spawn OUTSIDE the lock: a
                # slow child startup used to serialize EVERY hello (all
                # sessions, not just this token) behind this one spawn
                child = {"proc": None, "addr": None,
                         "event": threading.Event(), "error": None}
                self._children[token] = child
                spawn_needed = True
        if spawn_needed:
            try:
                spawned = self._spawn_child()
                child["proc"] = spawned["proc"]
                child["addr"] = spawned["addr"]
            except Exception as e:  # noqa: BLE001 - report to all waiters
                child["error"] = repr(e)
                with self._lock:
                    if self._children.get(token) is child:
                        self._children.pop(token)
                child["event"].set()
                raise
            child["event"].set()
        elif child["addr"] is None:
            # concurrent hello with the same token: wait (outside the
            # lock) for the in-flight spawn
            if not child["event"].wait(timeout=self._spawn_timeout + 5):
                raise RuntimeError("per-job client server spawn timed out")
            if child["error"] is not None:
                raise RuntimeError(
                    f"per-job client server spawn failed: {child['error']}")
        return {"redirect": list(child["addr"]), "session_token": token,
                "job_id": "proxied"}

    def stop(self):
        super().stop()
        with self._lock:
            children = list(self._children.values())
            self._children.clear()
        for c in children:
            try:
                c["proc"].terminate()
            except Exception:  # noqa: BLE001
                pass


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        prog="ray-tpu-client-proxier",
        description="per-job client server manager (proxier analog)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--address", help="GCS host:port to attach to")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--child-idle-exit", type=float, default=60.0)
    args = parser.parse_args(argv)

    gcs = None
    if args.address:
        host, _, port = args.address.rpartition(":")
        gcs = (host or "127.0.0.1", int(port))
    server = ProxyManager(args.host, args.port, gcs_address=gcs,
                          num_cpus=args.num_cpus,
                          child_idle_exit_s=args.child_idle_exit).start()
    print(f"client proxier on {server.address[0]}:{server.address[1]}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
