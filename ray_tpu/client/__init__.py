"""Client mode: remote driver over RPC (the ``ray://`` analog).

Reference analog: ``python/ray/util/client/`` (P6, 6,357 LoC — client-
side object refs + a server-side proxier). ``ray_tpu.init(
address="client://host:port")`` installs a :class:`ClientRuntime` whose
every API call (submit/get/put/wait/actors) is proxied to a
:class:`ray_tpu.client.server.ClientServer` process, which hosts the
REAL driver runtime (local or attached to a cluster). The client process
needs no raylet, no object store, and no worker pool — useful for
laptops/notebooks driving a remote TPU cluster.

Functions/classes ship as cloudpickle blobs; ObjectRefs cross the wire
as ids and stay server-owned (values move only on ``get``).
"""

from __future__ import annotations

import threading
import uuid

import cloudpickle

from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.rpc import ConnectionLost, ReconnectingRpcClient
from ray_tpu.runtime.task_spec import TaskSpec, TaskType
from ray_tpu.utils import exceptions as exc
from ray_tpu.utils.ids import ActorID, ObjectID


def parse_client_address(address: str) -> tuple[str, int] | None:
    """'client://host:port' -> (host, port); None for other schemes."""
    if not isinstance(address, str) or not address.startswith("client://"):
        return None
    rest = address[len("client://"):]
    host, _, port = rest.rpartition(":")
    if not port.isdigit():
        raise ValueError(f"bad client address {address!r}")
    return (host or "127.0.0.1", int(port))


class ClientSessionExpired(ConnectionError):
    """The server reaped this client's session (outage exceeded the
    reconnect grace): its refs/actors are gone, resuming would serve
    dangling handles — fail loudly (reference: ray client raises
    ConnectionError when the reconnect grace period is exceeded)."""


class _SessionRpcClient(ReconnectingRpcClient):
    """Redialing client that re-attaches the session after a redial so
    the server rebinds the new connection to the token (and cancels the
    pending session reap)."""

    def __init__(self, address, runtime: "ClientRuntime"):
        self._runtime = runtime
        self._session_lost = False
        super().__init__(address)

    def call(self, method, timeout=None, **kwargs):
        if self._session_lost:
            raise ClientSessionExpired(
                "client session expired: the server reaped it after the "
                "reconnect grace window; re-init() for a fresh session")
        try:
            return super().call(method, timeout=timeout, **kwargs)
        except (ConnectionLost, OSError) as e:
            if self._session_lost:   # the redial just discovered it
                raise ClientSessionExpired(
                    "client session expired during reconnect: the "
                    "server reaped it after the grace window") from e
            raise

    def _redial(self, failed, deadline=None) -> bool:
        if not super()._redial(failed, deadline):
            return False
        try:
            # direct call on the NEW underlying client: going through
            # self.call would recurse into redial on failure
            reply = self._client.call("client_hello",
                                      session_token=self._runtime._token)
        except (OSError, ConnectionLost):
            return False
        if not reply.get("resumed"):
            # the server created a FRESH session under our token: the
            # old one (and its refs/actors) is gone — don't silently
            # continue against dangling state
            self._session_lost = True
            return False
        return True


class ClientRuntime:
    """Thin proxy implementing the runtime interface api.py drives."""

    is_client = True

    def __init__(self, address: tuple[str, int]):
        # a stable session token survives connection drops: the wrapped
        # client redials and re-hellos, and the server resumes this
        # session's refs/actors within its reconnect grace window
        # (reference: client reconnect via _client_reconnect_grace)
        self._token = uuid.uuid4().hex
        self._rpc = _SessionRpcClient(address, self)
        self._lock = threading.Lock()
        info = self._rpc.call("client_hello", session_token=self._token)
        if info.get("redirect"):
            # per-job proxier (reference: proxier.py:113 ProxyManager):
            # the public endpoint spawned/located this session's OWN
            # server process — reconnect there and re-hello
            self._rpc.close()
            self._rpc = _SessionRpcClient(tuple(info["redirect"]), self)
            info = self._rpc.call("client_hello",
                                  session_token=self._token)
        self.job_id = info["job_id"]
        # -- incremental ref release on client GC (reference: the
        # client's ReleaseObject protocol, util/client/): dropped
        # client-side ObjectRefs release their server-side session hold
        # instead of pinning everything until disconnect. Installed only
        # when no other runtime in this process owns the ref-drain (a
        # same-process ClientServer test shares the global counter). --
        from ray_tpu.runtime import refcount as _refcount

        self._release_buf: list[str] = []
        self._release_lock = threading.Lock()
        self._closed = False
        self._track_gc = not _refcount.is_active()
        if self._track_gc:
            _refcount.global_counter.set_local_release(self._on_ref_zero)
            threading.Thread(target=self._release_loop, daemon=True,
                             name="client-ref-release").start()

    def _on_ref_zero(self, oid_hex: str):
        with self._release_lock:
            self._release_buf.append(oid_hex)

    def _release_loop(self):
        import time as _time

        from ray_tpu.runtime.refcount import global_counter

        while not self._closed:
            _time.sleep(0.2)
            global_counter.poll_local()   # fires _on_ref_zero
            with self._release_lock:
                batch, self._release_buf = self._release_buf, []
            if batch and not self._closed:
                try:
                    self._rpc.call("client_release", oids=batch)
                except Exception:  # noqa: BLE001 - requeue on failure
                    with self._release_lock:
                        self._release_buf[:0] = batch

    # -- objects --------------------------------------------------------

    def put(self, value) -> ObjectRef:
        oid = self._rpc.call("client_put",
                             blob=cloudpickle.dumps(value, protocol=5))
        return ObjectRef(ObjectID.from_hex(oid))

    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        out = self._rpc.call("client_get",
                             oids=[r.id.hex() for r in refs],
                             get_timeout=timeout)
        if out.get("error_blob") is not None:
            raise cloudpickle.loads(out["error_blob"])
        return cloudpickle.loads(out["values_blob"])

    def wait(self, refs, num_returns=1, timeout=None):
        out = self._rpc.call("client_wait",
                             oids=[r.id.hex() for r in refs],
                             num_returns=num_returns,
                             wait_timeout=timeout)
        by_id = {r.id.hex(): r for r in refs}
        return ([by_id[h] for h in out["ready"]],
                [by_id[h] for h in out["not_ready"]])

    def cancel(self, ref: ObjectRef, force: bool = False):
        self._rpc.call("client_cancel", oid=ref.id.hex(), force=force)

    def free(self, refs: list):
        self._rpc.call("client_free", oids=[r.id.hex() for r in refs])

    def note_return_owner(self, spec) -> None:
        pass  # ownership lives server-side

    # -- tasks ----------------------------------------------------------

    def _wire_args(self, spec: TaskSpec) -> bytes:
        args = [("__objref__", a.id.hex()) if isinstance(a, ObjectRef)
                else a for a in spec.args]
        kwargs = {k: ("__objref__", v.id.hex())
                  if isinstance(v, ObjectRef) else v
                  for k, v in spec.kwargs.items()}
        return cloudpickle.dumps((args, kwargs), protocol=5)

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        if spec.task_type == TaskType.ACTOR_TASK:
            out = self._rpc.call(
                "client_submit_actor_task",
                actor_id=spec.actor_id.hex(),
                method_name=spec.actor_method_name,
                name=spec.function_name,
                args_blob=self._wire_args(spec),
                num_returns=spec.num_returns,
                trace_ctx=spec.trace_ctx,
            )
        else:
            out = self._rpc.call(
                "client_submit_task",
                name=spec.function_name,
                fn_blob=cloudpickle.dumps(spec.function, protocol=5),
                args_blob=self._wire_args(spec),
                num_returns=spec.num_returns,
                resources=dict(spec.resources.resources),
                max_retries=spec.max_retries,
                retry_exceptions=spec.retry_exceptions,
                runtime_env=spec.runtime_env,
                trace_ctx=spec.trace_ctx,
            )
        refs = [ObjectRef(ObjectID.from_hex(h)) for h in out]
        spec.return_ids = [r.id for r in refs]
        return refs

    # -- actors ---------------------------------------------------------

    def create_actor(self, spec: TaskSpec, name: str | None = None,
                     namespace: str | None = None,
                     lifetime: str | None = None):
        out = self._rpc.call(
            "client_create_actor",
            name=name,
            namespace=namespace,
            lifetime=lifetime,
            class_name=spec.function_name,
            cls_blob=cloudpickle.dumps(spec.function, protocol=5),
            args_blob=self._wire_args(spec),
            resources=dict(spec.resources.resources),
            max_concurrency=spec.max_concurrency,
            max_restarts=spec.max_restarts,
            runtime_env=spec.runtime_env,
        )
        if out.get("error"):
            raise ValueError(out["error"])
        return ActorID.from_hex(out["actor_id"])

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._rpc.call("client_kill_actor", actor_id=actor_id.hex(),
                       no_restart=no_restart)

    def get_actor(self, name: str, namespace: str | None = None) -> ActorID:
        out = self._rpc.call("client_get_actor", name=name,
                             namespace=namespace)
        if out.get("error"):
            raise ValueError(out["error"])
        return ActorID.from_hex(out["actor_id"])

    # -- introspection --------------------------------------------------

    def cluster_resources(self) -> dict:
        return self._rpc.call("client_cluster_resources")["total"]

    def available_resources_snapshot(self) -> dict:
        return self._rpc.call("client_cluster_resources")["available"]

    def task_events(self, limit: int = 1000) -> list:
        return self._rpc.call("client_task_events", limit=limit)

    def actor_state(self, actor_id: ActorID):
        return None  # class names resolve server-side only

    def shutdown(self):
        self._closed = True
        if self._track_gc:
            from ray_tpu.runtime.refcount import global_counter

            global_counter.set_local_release(None)
        try:
            # direct call on the live underlying connection: a goodbye
            # to a dead server must not spend the 10s redial window, and
            # ConnectionLost must not escape a teardown path
            self._rpc._client.call("client_disconnect")
        except (OSError, ConnectionLost, exc.RayTpuError,
                ClientSessionExpired):
            pass
        self._rpc.close()
