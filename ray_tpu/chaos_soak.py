"""Chaos soak harness: seeded crash/partition schedule over a mixed
workload, with conservation invariants.

The tentpole acceptance driver for the crash chaos plane
(``runtime/fault_injection.py`` crash rules + the recovery paths:
raylet worker respawn, cluster raylet/GCS supervision, serve replica
failover). One soak run:

1. builds a supervised multi-node cluster (external fault-tolerant GCS,
   external raylets) and a serve deployment,
2. drives three concurrent workloads — plain tasks, an actor, serve
   calls + streams — for ``duration_s``,
3. replays a SEEDED schedule of fault injections: crash plans switched
   through the GCS KV plan key (worker / replica / raylet / GCS crash
   points) plus metrics-plane partitions,
4. asserts conservation at the end: every submitted op's ``get()``
   resolved or raised a TYPED ``RayTpuError`` (never a bare redial
   ``TimeoutError``), nothing wedged in ``stuck_calls()``, no fd or
   thread leaks in the driver, and the observability planes still
   answer,
5. records per-fault-class MTTR (see ``docs/crash_chaos.md`` for the
   per-class definitions) into a ``CHAOS_*.json`` style document.

Same seed + same classes ⇒ same injection schedule: the schedule RNG is
``random.Random(seed)`` and every crash rule carries the plan seed, so a
failure reproduces by re-running with the seed printed in the report.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time


FAULT_CLASSES = ("worker", "replica", "raylet", "gcs")

# crash-plan templates per fault class: what the KV switch installs for
# one injection window (nth=1, max_hits=1 ⇒ at most one death per
# process that reaches the point while the window is open)
_CLASS_RULES = {
    "worker": [
        {"id": "soak-worker-task", "fault": "crash",
         "point": "worker.mid_task", "proc": "worker",
         "nth": 1, "max_hits": 1},
        {"id": "soak-actor", "fault": "crash",
         "point": "soak.actor_bump", "proc": "worker",
         "nth": 1, "max_hits": 1},
    ],
    "replica": [
        {"id": "soak-replica", "fault": "crash",
         "point": "replica.mid_*", "proc": "worker",
         "nth": 1, "max_hits": 1},
    ],
    "raylet": [
        {"id": "soak-raylet", "fault": "crash",
         "point": "raylet.before_lease_grant", "proc": "raylet",
         "nth": 1, "max_hits": 1},
    ],
    "gcs": [
        {"id": "soak-gcs", "fault": "crash",
         "point": "gcs.after_wal_append", "proc": "gcs",
         "nth": 1, "max_hits": 1},
    ],
}


class _Workload:
    """One workload loop's ledger: every submitted op ends up as exactly
    one record, so conservation is checkable by scanning the ledger."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.ops: list[dict] = []

    def record(self, submitted: float, done: float, ok: bool,
               error: BaseException | None = None):
        from ray_tpu.utils.exceptions import RayTpuError
        rec = {"submitted": submitted, "done": done, "ok": ok}
        if error is not None:
            rec["error"] = type(error).__name__
            rec["typed"] = isinstance(error, RayTpuError)
            rec["detail"] = repr(error)[:200]
        with self.lock:
            self.ops.append(rec)

    def summary(self) -> dict:
        with self.lock:
            ops = list(self.ops)
        out = {"submitted": len(ops),
               "ok": sum(1 for o in ops if o["ok"]),
               "typed_errors": sum(1 for o in ops
                                   if not o["ok"] and o.get("typed")),
               "untyped_errors": sum(1 for o in ops
                                     if not o["ok"] and not o.get("typed"))}
        return out

    def untyped(self) -> list[dict]:
        with self.lock:
            return [o for o in self.ops
                    if not o["ok"] and not o.get("typed")]

    def first_ok_after(self, t: float) -> float | None:
        """done-timestamp of the earliest successful op SUBMITTED after
        t — the workload-visible recovery point for a fault at t."""
        with self.lock:
            cands = [o["done"] for o in self.ops
                     if o["ok"] and o["submitted"] > t]
        return min(cands) if cands else None


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def run_soak(duration_s: float = 300.0, seed: int = 0,
             classes=FAULT_CLASSES, *, inject_period_s: float = 8.0,
             partitions: bool = True, serve_replicas: int = 2,
             get_timeout_s: float = 30.0, log=print) -> dict:
    """Run one seeded soak; returns the report dict (see module doc)."""
    # children (raylets, GCS, workers) inherit the switch; the driver's
    # own plane stays consulted-but-unarmed (crash rules never match
    # proc="driver" in the schedule below). Restored on exit: leaking
    # the switch into the host process flips fault-plane behavior for
    # whatever runs next (e.g. later tests in one pytest process).
    env_prev = {k: os.environ.get(k)
                for k in ("RAY_TPU_FAULT_INJECTION_ENABLED",
                          "RAY_TPU_FAULT_INJECTION_SEED")}
    os.environ["RAY_TPU_FAULT_INJECTION_ENABLED"] = "1"
    os.environ.setdefault("RAY_TPU_FAULT_INJECTION_SEED", str(seed))

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.runtime import fault_injection as fi
    from ray_tpu.utils.exceptions import ActorError, GetTimeoutError

    classes = tuple(classes)
    rng = random.Random(seed)
    report: dict = {"bench": "chaos_soak", "seed": seed,
                    "duration_s": duration_s, "classes": list(classes)}
    violations: list[dict] = []

    cluster = Cluster(heartbeat_timeout_s=2.0, gcs_fault_tolerance=True,
                      external_gcs=("gcs" in classes))
    try:
        cluster.add_node(num_cpus=8)
        n_nodes = 1
        if "raylet" in classes:
            # the head's in-process raylet keeps the driver label and is
            # exempt from proc="raylet" rules; tag the external nodes
            # with a capacity the head lacks so a slice of the workload
            # MUST lease there — raylet.before_lease_grant is then
            # evaluated continuously on a killable raylet and the
            # raylet fault class fires deterministically in its window
            cluster.add_node(num_cpus=4, external=True,
                             resources={"ext": 4})
            cluster.add_node(num_cpus=4, external=True,
                             resources={"ext": 4})
            n_nodes = 3
        cluster.wait_for_nodes(n_nodes, timeout=30)
        cluster.start_supervisor(poll_s=0.2)
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote
        def soak_task(x):
            return x * 2

        @ray_tpu.remote
        class SoakCounter:
            def __init__(self):
                self.n = 0

            def bump(self):
                from ray_tpu.runtime import fault_injection as _fi
                _fi.maybe_crash("soak.actor_bump")
                self.n += 1
                return self.n

        @serve.deployment(num_replicas=serve_replicas,
                          max_concurrent_queries=8)
        class SoakEcho:
            def __call__(self, x):
                return {"echo": x}

            def chunks(self, n):
                for i in range(n):
                    yield i

        handle = serve.run(SoakEcho.bind())
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")

        stop = threading.Event()
        ledgers = {"tasks": _Workload("tasks"),
                   "actor": _Workload("actor"),
                   "serve": _Workload("serve")}
        if "raylet" in classes:
            ledgers["tasks_ext"] = _Workload("tasks_ext")

        def classify(led: _Workload, t0: float, err: BaseException):
            led.record(t0, time.time(), ok=False, error=err)

        def tasks_loop():
            i = 0
            led = ledgers["tasks"]
            while not stop.is_set():
                t0 = time.time()
                try:
                    out = ray_tpu.get(soak_task.remote(i),
                                      timeout=get_timeout_s)
                    led.record(t0, time.time(), ok=(out == i * 2))
                except Exception as e:  # noqa: BLE001 - ledger classifies
                    classify(led, t0, e)
                i += 1
                stop.wait(0.05)

        def tasks_ext_loop():
            # external-pinned slice: {"ext"} only exists on the external
            # raylets, so every lap grants a lease on one of them — the
            # workload that proves the raylet fault class fires and that
            # leases flow again after the supervisor respawn
            i = 0
            led = ledgers["tasks_ext"]
            ext_task = soak_task.options(resources={"ext": 1})
            while not stop.is_set():
                t0 = time.time()
                try:
                    out = ray_tpu.get(ext_task.remote(i),
                                      timeout=get_timeout_s)
                    led.record(t0, time.time(), ok=(out == i * 2))
                except Exception as e:  # noqa: BLE001 - ledger classifies
                    classify(led, t0, e)
                i += 1
                stop.wait(0.05)

        def actor_loop():
            led = ledgers["actor"]
            actor = SoakCounter.remote()
            while not stop.is_set():
                t0 = time.time()
                try:
                    ray_tpu.get(actor.bump.remote(),
                                timeout=get_timeout_s)
                    led.record(t0, time.time(), ok=True)
                except ActorError as e:
                    # typed death: replace the actor and keep going —
                    # exactly what a supervisor-style app would do
                    classify(led, t0, e)
                    try:
                        actor = SoakCounter.remote()
                    except Exception:  # noqa: BLE001 - retried next lap
                        pass
                except Exception as e:  # noqa: BLE001
                    classify(led, t0, e)
                stop.wait(0.1)

        def serve_loop():
            led = ledgers["serve"]
            i = 0
            stream_handle = handle.options(method_name="chunks")
            while not stop.is_set():
                t0 = time.time()
                try:
                    if i % 5 == 4:
                        got = list(stream_handle.stream(3))
                        led.record(t0, time.time(), ok=(got == [0, 1, 2]))
                    else:
                        out = handle.call(i)
                        led.record(t0, time.time(),
                                   ok=(out == {"echo": i}))
                except Exception as e:  # noqa: BLE001
                    classify(led, t0, e)
                i += 1
                stop.wait(0.1)

        loops = [("soak-tasks", tasks_loop),
                 ("soak-actor", actor_loop),
                 ("soak-serve", serve_loop)]
        if "tasks_ext" in ledgers:
            loops.append(("soak-tasks-ext", tasks_ext_loop))
        threads = [threading.Thread(target=fn, daemon=True, name=name)
                   for name, fn in loops]
        for t in threads:
            t.start()

        # the GCS log store is rebuilt empty on a crash-restart (error
        # groups are not WAL'd), so a crash group harvested before the
        # run's last GCS death is gone by the final check — poll live
        # and latch the sighting instead
        crash_group_live = threading.Event()

        def crash_group_poll():
            from ray_tpu.util import state as state_api
            while not stop.is_set():
                try:
                    if any(g.get("kind") == "crash"
                           for g in state_api.summarize_errors()):
                        crash_group_live.set()
                        return
                except Exception:  # noqa: BLE001 - GCS mid-restart
                    pass
                stop.wait(2.0)

        poller = threading.Thread(target=crash_group_poll, daemon=True,
                                  name="soak-crash-group-poll")
        poller.start()

        # warm up, then baseline the leak counters
        time.sleep(3.0)
        fd0, threads0 = _fd_count(), threading.active_count()

        # -- seeded injection schedule ---------------------------------
        version = 1
        injections: list[dict] = []
        fault_menu = list(classes) + (["partition"] if partitions else [])

        def put(rules, *, attempts=20):
            nonlocal version
            version += 1
            plan = {"version": version, "seed": seed, "rules": rules}
            last = None
            for _ in range(attempts):
                try:
                    fi.put_plan(cluster.gcs_address, plan)
                    return True
                except Exception as e:  # noqa: BLE001 - GCS mid-restart
                    last = e
                    time.sleep(0.5)
            log(f"[soak] plan write failed after retries: {last!r}")
            return False

        t_end = time.monotonic() + duration_s
        while time.monotonic() < t_end - max(6.0, inject_period_s):
            cls = rng.choice(fault_menu)
            t0 = time.time()
            ev = {"class": cls, "t": t0, "recovered_s": None}
            if cls == "partition":
                # sever the metrics push channel (observability
                # degrades, conservation must not): a known-survivable
                # cut exercised by tests/test_chaos_partitions.py
                put([{"id": "soak-cut-metrics", "fault": "partition",
                      "src": "metrics", "dst": "gcs",
                      "direction": "both"}])
                time.sleep(rng.uniform(1.0, 2.5))
                put([])
            else:
                put(list(_CLASS_RULES[cls]))
                # the window: processes that reach the point die once
                time.sleep(rng.uniform(1.5, 3.0))
                # clear; for the gcs class this very write IS the
                # trigger (WAL append → crash before reply), so it can
                # raise — the retry loop rides out the restart
                put([])
            injections.append(ev)
            log(f"[soak] injected {cls} at +"
                f"{duration_s - (t_end - time.monotonic()):.0f}s")
            # let the dust settle so per-class recoveries attribute to
            # the right injection
            time.sleep(max(0.0, inject_period_s - 3.0)
                       * rng.uniform(0.8, 1.2))

        # make sure no crash rules stay armed, then drain
        put([])
        settle = min(20.0, max(10.0, get_timeout_s / 2))
        time.sleep(settle)
        stop.set()
        # "wedged" must mean UNBOUNDED, not merely slow: a call racing
        # the last injection can legitimately sit in actor-location
        # resolve for up to actor_resolve_timeout_s before it surfaces
        # typed, so the join window sizes past the system's worst-case
        # bounded resolution latency (join returns early when threads
        # finish, which is the common case)
        from ray_tpu.utils.config import get_config as _gc
        join_s = max(get_timeout_s + 10,
                     _gc().actor_resolve_timeout_s + 30)
        for t in threads:
            t.join(timeout=join_s)
        wedged_threads = [t.name for t in threads if t.is_alive()]
        # a wedged workload is the invariant failure this harness
        # exists to catch — capture WHERE it is stuck so the report is
        # actionable, not just red
        wedge_stacks: dict[str, list[str]] = {}
        if wedged_threads:
            import traceback
            frames = sys._current_frames()
            for t in threads:
                if t.is_alive() and t.ident in frames:
                    wedge_stacks[t.name] = [
                        ln.strip() for ln in traceback.format_stack(
                            frames[t.ident])[-8:]]

        # -- MTTR accounting -------------------------------------------
        per_class: dict[str, dict] = {}
        failover = ray_tpu.get(controller.failover_stats.remote(),
                               timeout=20)
        replica_mttrs = [e["replaced_at"] - e["detected_at"]
                         for e in failover["events"]
                         if e.get("replaced_at")]
        cluster_events = list(cluster.crash_events)
        raylet_mttrs = [e["recovered_at"] - e["detected_at"]
                        for e in cluster_events if e["class"] == "raylet"]
        gcs_mttrs = [e["recovered_at"] - e["detected_at"]
                     for e in cluster_events if e["class"] == "gcs"]
        service_ledger = {"worker": "tasks", "replica": "serve",
                          "raylet": "tasks_ext"}
        for ev in injections:
            led = ledgers.get(service_ledger.get(ev["class"]))
            if led is not None:
                ok_at = led.first_ok_after(ev["t"])
                if ok_at is not None:
                    ev["recovered_s"] = ok_at - ev["t"]
        for cls in classes:
            evs = [e for e in injections if e["class"] == cls]
            service = [e["recovered_s"] for e in evs
                       if e["recovered_s"] is not None]
            entry = {"injections": len(evs),
                     "service_mttr_s": service}
            if cls == "replica":
                entry["replace_mttr_s"] = replica_mttrs
            if cls == "raylet":
                entry["respawn_mttr_s"] = raylet_mttrs
            if cls == "gcs":
                entry["restart_mttr_s"] = gcs_mttrs
            for key in ("service_mttr_s", "replace_mttr_s",
                        "respawn_mttr_s", "restart_mttr_s"):
                vals = entry.get(key)
                if vals:
                    entry[key.replace("_s", "_mean_s")] = (
                        sum(vals) / len(vals))
                    entry[key.replace("_s", "_max_s")] = max(vals)
            per_class[cls] = entry

        # -- invariants ------------------------------------------------
        for name, led in ledgers.items():
            for op in led.untyped():
                violations.append({"invariant": "typed_errors",
                                   "workload": name, **op})
        for name in wedged_threads:
            violations.append({"invariant": "no_wedged_workloads",
                               "workload": name,
                               "stack": wedge_stacks.get(name)})
        if "raylet" in classes and not raylet_mttrs and any(
                e["class"] == "raylet" for e in injections):
            violations.append({"invariant": "raylet_respawned",
                               "detail": "no supervisor respawn event"})
        if "gcs" in classes and not gcs_mttrs and any(
                e["class"] == "gcs" for e in injections):
            violations.append({"invariant": "gcs_restarted",
                               "detail": "no supervisor restart event"})
        if "replica" in classes and any(
                e["class"] == "replica" for e in injections):
            if not failover["events"]:
                violations.append({
                    "invariant": "replica_replaced",
                    "detail": "controller recorded no failover events"})

        from ray_tpu.util import state as state_api
        stuck = state_api.stuck_calls(threshold_s=get_timeout_s)
        n_stuck = len(stuck.get("driver") or [])
        gcs_calls = stuck.get("gcs")
        if isinstance(gcs_calls, list):
            n_stuck += len(gcs_calls)
        for calls in (stuck.get("nodes") or {}).values():
            if isinstance(calls, dict):
                calls = calls.get("calls")
            if isinstance(calls, list):
                n_stuck += len(calls)
        if n_stuck:
            violations.append({"invariant": "no_stuck_calls",
                               "count": n_stuck})

        fd1, threads1 = _fd_count(), threading.active_count()
        fd_delta = (fd1 - fd0) if fd0 >= 0 and fd1 >= 0 else 0
        thread_delta = threads1 - threads0
        if fd_delta > 64:
            violations.append({"invariant": "no_fd_leak",
                               "delta": fd_delta})
        if thread_delta > 16:
            violations.append({"invariant": "no_thread_leak",
                               "delta": thread_delta})

        planes = {}
        try:
            errs = state_api.summarize_errors()
            planes["log"] = isinstance(errs, list)
            planes["crash_group_seen"] = (
                any(g.get("kind") == "crash" for g in errs)
                or crash_group_live.is_set())
        except Exception as e:  # noqa: BLE001
            planes["log"] = False
            violations.append({"invariant": "planes_intact",
                               "plane": "log", "detail": repr(e)[:200]})
        try:
            planes["metrics"] = isinstance(
                state_api.cluster_metrics(), dict)
        except Exception as e:  # noqa: BLE001
            planes["metrics"] = False
            violations.append({"invariant": "planes_intact",
                               "plane": "metrics",
                               "detail": repr(e)[:200]})
        try:
            planes["trace"] = isinstance(state_api.list_traces(5), list)
        except Exception as e:  # noqa: BLE001
            planes["trace"] = False
            violations.append({"invariant": "planes_intact",
                               "plane": "trace", "detail": repr(e)[:200]})
        crash_injected = any(e["class"] in ("worker", "replica")
                             for e in injections)
        if crash_injected and not planes.get("crash_group_seen"):
            violations.append({
                "invariant": "crash_last_words_harvested",
                "detail": "no 'crash' group in summarize_errors()"})

        report.update({
            "injections": injections,
            "per_class": per_class,
            "workloads": {n: led.summary()
                          for n, led in ledgers.items()},
            "replica_failover": failover,
            "cluster_events": [
                {k: v for k, v in e.items() if k != "last_words"}
                for e in cluster_events],
            "stuck_calls": n_stuck,
            "fd_delta": fd_delta, "thread_delta": thread_delta,
            "planes": planes,
            "violations": violations,
            "chaos_soak_invariant_violations": len(violations),
        })
        # flat gate metrics (ci/perf_gate.py ceilings)
        rep = per_class.get("replica", {})
        ray_cls = per_class.get("raylet", {})
        if rep.get("replace_mttr_mean_s") is not None:
            report["chaos_mttr_replica_mean_s"] = rep[
                "replace_mttr_mean_s"]
        if ray_cls.get("respawn_mttr_mean_s") is not None:
            report["chaos_mttr_raylet_mean_s"] = ray_cls[
                "respawn_mttr_mean_s"]
        return report
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        cluster.shutdown()
        try:
            fi.stop_kv_watcher()
            fi.plane.clear()
        except Exception:  # noqa: BLE001
            pass
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def measure_probe_overhead(pings: int = 200) -> dict:
    """Amortized health-probe tax on a serving replica. The controller
    pings each replica once per ``serve_health_probe_period_s``; the
    replica-side cost per probe is bounded above by the full ping RTT
    (handling is a subset of the round trip). Ratio = probe rate x
    min-of-k RTT = worst-case fraction of a replica's wall-clock spent
    answering probes — ci/perf_gate.py fences it under 1%
    (serve_probe_overhead_ratio), the ISSUE-16 guard that proactive
    failover does not tax serving throughput."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.config import get_config

    ray_tpu.shutdown()
    cluster = Cluster(heartbeat_timeout_s=3.0)
    cluster.add_node(num_cpus=4)
    ray_tpu.init(address=cluster.gcs_address)
    try:
        @serve.deployment(num_replicas=1)
        class _Probe:
            def __call__(self, x):
                return x

        h = serve.run(_Probe.bind(), name="probe_overhead")
        assert h.call(0) == 0
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        _, replicas = ray_tpu.get(
            controller.get_replicas.remote("probe_overhead"))
        replica = replicas[0]
        for _ in range(10):   # warm the direct actor channel + codec
            ray_tpu.get(replica.ping.remote())
        # PIPELINED pings: a sequential RTT loop would charge the
        # driver's own completion-poll latency (~tens of ms, zero
        # replica cost) to the replica. Submitting the burst up front
        # amortizes that wait away; per-ping wall time then tracks the
        # replica-side handling cost the probes actually tax.
        best = float("inf")
        for _ in range(3):    # min-of-k bursts, like the other probes
            t0 = time.perf_counter()
            ray_tpu.get([replica.ping.remote() for _ in range(pings)])
            best = min(best, (time.perf_counter() - t0) / pings)
        cfg = get_config()
        rate = 1.0 / cfg.serve_health_probe_period_s
        return {"ping_cost_s": best,
                "probes_per_replica_per_s": rate,
                "ratio": best * rate}
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        cluster.shutdown()


def run_soak_matrix(duration_s: float, seeds, classes=FAULT_CLASSES,
                    out_path: str | None = None, log=print, **kw) -> dict:
    """Run one soak per seed and merge: violations sum, MTTR gate
    metrics take the worst seed. The merged doc is what CI fences."""
    runs = {}
    for s in seeds:
        log(f"[soak] ==== seed {s} ====")
        runs[str(s)] = run_soak(duration_s, int(s), classes,
                                log=log, **kw)
    merged: dict = {"bench": "chaos_soak",
                    "seeds": [int(s) for s in seeds],
                    "duration_s": duration_s,
                    "classes": list(classes),
                    "runs": runs}
    merged["chaos_soak_invariant_violations"] = sum(
        r["chaos_soak_invariant_violations"] for r in runs.values())
    for key in ("chaos_mttr_replica_mean_s", "chaos_mttr_raylet_mean_s"):
        vals = [r[key] for r in runs.values() if key in r]
        if vals:
            merged[key] = max(vals)
    try:
        merged["probe_overhead"] = measure_probe_overhead()
        log(f"[soak] probe overhead ratio "
            f"{merged['probe_overhead']['ratio']:.5f}")
    except Exception as e:  # noqa: BLE001 - guard rides the bench doc
        merged["probe_overhead"] = {"error": repr(e)}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f, indent=2, default=str)
        log(f"[soak] wrote {out_path}")
    return merged
