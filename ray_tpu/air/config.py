"""Shared run/scale configs (reference: ``python/ray/air/config.py`` —
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class ScalingConfig:
    """How many rank workers and what each needs.

    ``topology`` optionally names a TPU slice shape (e.g. "v5p-16") so
    slice-aware placement can keep ranks ICI-adjacent (reference analog:
    TPU autodetect + PG-backed WorkerGroup; SURVEY §2c elastic row)."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: dict = field(default_factory=dict)
    topology: str | None = None
    placement_strategy: str = "PACK"

    def worker_resources(self) -> dict:
        r = dict(self.resources_per_worker)
        r.setdefault("CPU", 1.0)
        if self.use_tpu:
            r.setdefault("TPU", 1.0)
        return r


@dataclass
class FailureConfig:
    max_failures: int = 0   # trial-level retries (reference semantics)


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None           # top-k retention
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"      # "max" | "min"


@dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(base, self.name) if self.name else base
