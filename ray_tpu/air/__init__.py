"""ray_tpu.air: shared configs + execution glue (reference: SURVEY P17,
``python/ray/air/``)."""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)

__all__ = [
    "CheckpointConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
]
