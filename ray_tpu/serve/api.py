"""Serve public API: @deployment / run / batch / HTTP proxy.

Reference analog: ``serve/api.py`` (``@serve.deployment:256``,
``serve.run:463``), ``serve/batching.py`` (``@serve.batch:65`` dynamic
batching), and the per-node HTTP proxy (``_private/proxy.py:759`` — here a
threaded stdlib HTTP server routing JSON bodies to deployment handles,
keeping the data path dependency-free)."""

from __future__ import annotations

import functools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.handle import DeploymentHandle

CONTROLLER_NAME = "SERVE_CONTROLLER"
_local = threading.local()


def _get_or_start_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    controller_cls = ray_tpu.remote(ServeController)
    try:
        # detached: the serve app outlives the driver that started it
        # (reference: serve's controller runs detached)
        return controller_cls.options(name=CONTROLLER_NAME,
                                      max_concurrency=16,
                                      lifetime="detached").remote()
    except ValueError:  # raced another starter
        return ray_tpu.get_actor(CONTROLLER_NAME)


class Deployment:
    """Bound result of @serve.deployment on a class."""

    def __init__(self, cls, name: str, config: DeploymentConfig,
                 init_args=(), init_kwargs=None):
        self._cls = cls
        self.name = name
        self.config = config
        self._init_args = init_args
        self._init_kwargs = init_kwargs or {}

    def options(self, *, name=None, num_replicas=None,
                max_concurrent_queries=None, autoscaling_config=None,
                user_config=None, resources_per_replica=None) -> "Deployment":
        cfg = DeploymentConfig(
            num_replicas=num_replicas or self.config.num_replicas,
            max_concurrent_queries=(max_concurrent_queries
                                    or self.config.max_concurrent_queries),
            autoscaling=autoscaling_config or self.config.autoscaling,
            user_config=user_config or self.config.user_config,
            resources_per_replica=(resources_per_replica
                                   or self.config.resources_per_replica),
        )
        return Deployment(self._cls, name or self.name, cfg,
                          self._init_args, self._init_kwargs)

    def bind(self, *args, **kwargs) -> "Deployment":
        """Bind constructor args (reference: DAG .bind())."""
        return Deployment(self._cls, self.name, self.config, args, kwargs)


def deployment(cls=None, *, name=None, num_replicas=1,
               max_concurrent_queries=8, autoscaling_config=None,
               user_config=None, resources_per_replica=None):
    def wrap(c):
        auto = autoscaling_config
        if isinstance(auto, dict):
            auto = AutoscalingConfig(**auto)
        return Deployment(
            c, name or c.__name__,
            DeploymentConfig(
                num_replicas=num_replicas,
                max_concurrent_queries=max_concurrent_queries,
                autoscaling=auto,
                user_config=user_config or {},
                resources_per_replica=resources_per_replica or {},
            ))
    return wrap(cls) if cls is not None else wrap


class DeploymentRef:
    """Picklable placeholder for a nested deployment in init args; the
    replica resolves it into a live DeploymentHandle at construction
    (reference: deployment-graph composition — passing one bound
    deployment into another's ``.bind()``)."""

    def __init__(self, name: str):
        self.name = name


def _deploy_nested(value, seen: dict):
    """Depth-first deploy of Deployment objects found in init args;
    returns the value with each replaced by a DeploymentRef. ``seen``
    maps deployment name -> the Deployment node already deployed under
    it; two distinct bind nodes sharing a name is an error (they would
    silently alias to one deployment), so composition with the same
    class twice requires ``.options(name=...)``."""
    if isinstance(value, Deployment):
        prior = seen.get(value.name)
        if prior is None:
            seen[value.name] = value
            run(value, _seen=seen)
        elif prior is not value and not (
                prior._cls is value._cls
                and prior._init_args == value._init_args
                and prior._init_kwargs == value._init_kwargs):
            raise ValueError(
                f"two different deployments named {value.name!r} in one "
                "graph; disambiguate with .options(name=...)")
        return DeploymentRef(value.name)
    if isinstance(value, tuple):
        walked = [_deploy_nested(v, seen) for v in value]
        # namedtuples construct positionally, not from an iterable
        return (type(value)(*walked) if hasattr(value, "_fields")
                else tuple(walked))
    if isinstance(value, list):
        return [_deploy_nested(v, seen) for v in value]
    if isinstance(value, dict):
        return {k: _deploy_nested(v, seen) for k, v in value.items()}
    return value


def run(dep: Deployment, *, name: str | None = None,
        _seen: set | None = None) -> DeploymentHandle:
    """Deploy (or redeploy) and return a handle (reference: serve.run:463).

    Composition: any ``Deployment`` nested in the bound init args (incl.
    inside lists/dicts) is deployed first and the replica receives a
    live ``DeploymentHandle`` in its place — the deployment-graph
    pattern (``outer.bind(inner.bind())``)."""
    controller = _get_or_start_controller()
    seen = _seen if _seen is not None else {(name or dep.name): dep}
    dep = Deployment(dep._cls, dep.name, dep.config,
                     _deploy_nested(list(dep._init_args), seen),
                     _deploy_nested(dict(dep._init_kwargs), seen))
    auto = dep.config.autoscaling
    cfg = {
        "num_replicas": dep.config.num_replicas,
        "max_concurrent_queries": dep.config.max_concurrent_queries,
        "autoscaling": vars(auto) if auto else None,
        "user_config": dep.config.user_config,
        "resources_per_replica": dep.config.resources_per_replica,
        # ASGI ingress deployments get raw-request forwarding from the
        # proxies (serve.ingress sets the marker)
        "asgi": bool(getattr(dep._cls, "_serve_asgi", False)),
    }
    dep_name = name or dep.name
    ray_tpu.get(controller.deploy.remote(
        dep_name, cloudpickle.dumps(dep._cls, protocol=5),
        dep._init_args, dep._init_kwargs, cfg))
    handle = DeploymentHandle(dep_name, controller)
    # wait for at least one replica
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        version, replicas = ray_tpu.get(
            controller.get_replicas.remote(dep_name))
        if replicas:
            return handle
        time.sleep(0.05)
    raise TimeoutError(f"deployment {dep_name!r} has no replicas after 30s")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_start_controller())


def status() -> dict:
    """Deployment states + replica metrics (reference: serve.status() /
    the REST status schema)."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {"deployments": {}}
    deployments = ray_tpu.get(controller.list_deployments.remote())
    out = {}
    for name, info in deployments.items():
        _, replicas = ray_tpu.get(controller.get_replicas.remote(name))
        metrics = []
        for r in replicas or []:
            try:
                metrics.append(ray_tpu.get(r.metrics.remote(), timeout=2))
            except Exception:  # noqa: BLE001 - replica mid-teardown
                continue
        out[name] = {
            **info,
            "replica_metrics": metrics,
            "total_requests": sum(m.get("total", 0) for m in metrics),
            "ongoing_requests": sum(m.get("ongoing", 0) for m in metrics),
        }
    return {"deployments": out}


def delete(name: str):
    controller = _get_or_start_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))


def shutdown():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=10)
    except Exception:  # noqa: BLE001
        pass
    ray_tpu.kill(controller)


# ---------------------------------------------------------------------------
# dynamic batching (reference: serve/batching.py:65)
# ---------------------------------------------------------------------------

def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``fn(self, items: list) -> list`` is invoked with batches
    accumulated across concurrent callers (requires the deployment's
    max_concurrent_queries > 1 so callers overlap)."""

    def wrap(fn):
        # batching state lives on the replica INSTANCE, created lazily —
        # the decorator closure must stay pickle-clean (the deployment
        # class ships to replicas via cloudpickle). The wrapper is a
        # COROUTINE: replicas are asyncio actors, so concurrent callers
        # are coroutines on one loop — accumulation is cooperative
        # (futures + a timed shield), no threads or locks.
        attr = f"__serve_batch_state_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, item):
            import asyncio
            import inspect

            state = self.__dict__.setdefault(attr, {"queue": []})
            entry = {"item": item,
                     "fut": asyncio.get_running_loop().create_future()}
            state["queue"].append(entry)
            if len(state["queue"]) < max_batch_size:
                # linger for batchmates; shield() keeps a timeout from
                # cancelling a future another flusher may yet complete
                try:
                    await asyncio.wait_for(asyncio.shield(entry["fut"]),
                                           timeout=batch_wait_timeout_s)
                except (asyncio.TimeoutError, Exception):  # noqa: BLE001
                    pass
            # Flush until OUR entry completes: a caller may flush batches
            # that don't contain its own entry (they were queued first);
            # it then loops and flushes the next batch rather than
            # stranding itself.
            while not entry["fut"].done():
                batch_entries = state["queue"][:max_batch_size]
                state["queue"] = state["queue"][max_batch_size:]
                if not batch_entries:
                    await asyncio.sleep(0.005)
                    continue
                try:
                    if inspect.iscoroutinefunction(fn):
                        results = await fn(
                            self, [e["item"] for e in batch_entries])
                    else:
                        # sync batch fn (the common case: a blocking
                        # model call) runs OFF the loop — freezing the
                        # replica's loop for a whole batch would stall
                        # accumulation of the next batch and every other
                        # call on the replica
                        import functools as _ft

                        results = await asyncio.get_running_loop() \
                            .run_in_executor(None, _ft.partial(
                                fn, self,
                                [e["item"] for e in batch_entries]))
                        if inspect.isawaitable(results):
                            results = await results
                    for e, r in zip(batch_entries, results):
                        if not e["fut"].done():
                            e["fut"].set_result(r)
                except BaseException as err:  # noqa: BLE001
                    for e in batch_entries:
                        if not e["fut"].done():
                            e["fut"].set_exception(err)
            return await entry["fut"]   # done: value or raise

        wrapper.__wrapped_batch__ = fn
        return wrapper

    return wrap if _fn is None else wrap(_fn)


# ---------------------------------------------------------------------------
# HTTP proxy (reference: _private/proxy.py — uvicorn HTTP; stdlib here)
# ---------------------------------------------------------------------------

class _ProxyHandler(BaseHTTPRequestHandler):
    # handle cache is per proxy server: start_http_proxy subclasses this
    # with a fresh dict (a class-level cache would leak stale controller
    # references across serve.shutdown()/restart cycles)
    handles: dict[str, DeploymentHandle]
    asgi_flags: dict[str, bool]

    def log_message(self, *args):  # silence request logging
        pass

    def _resolve(self, name: str):
        handle = self.handles.get(name)
        if handle is None:
            handle = get_deployment_handle(name)
            handle._refresh(ttl=0)  # raises KeyError if unknown
            self.handles[name] = handle
        asgi = self.asgi_flags.get(name)
        if asgi is None:
            import ray_tpu

            meta = ray_tpu.get(
                handle._controller.deployment_meta.remote(name))
            asgi = bool(meta.get("asgi"))
            self.asgi_flags[name] = asgi
        return handle, asgi

    def _reply(self, code: int, body: bytes,
               content_type: str = "application/json", headers=()):
        self.send_response(code)
        sent_ct = False
        for k, v in headers:
            if k.lower() == "content-length":
                continue  # we recompute it
            if k.lower() == "content-type":
                sent_ct = True
            self.send_header(k, v)
        if not sent_ct:
            self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _route(self):
        if self.path in ("/-/healthz", "/healthz"):
            self._reply(200, b"ok", "text/plain")
            return
        from urllib.parse import urlsplit

        split = urlsplit(self.path)
        parts = split.path.strip("/").split("/", 1)
        name = parts[0]
        subpath = "/" + (parts[1] if len(parts) > 1 else "")
        try:
            handle, asgi = self._resolve(name)
        except Exception:  # noqa: BLE001
            self.send_error(404, f"no deployment {name!r}")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        try:
            if asgi:
                # raw-request forwarding: the replica's mounted ASGI app
                # owns routing/methods/content types
                out = handle.call({
                    "__raw__": True, "method": self.command,
                    "path": subpath, "query_string": split.query,
                    "headers": list(self.headers.items()), "body": body,
                })
                self._reply(out.get("status", 500),
                            out.get("body", b""),
                            headers=out.get("headers", ()))
                return
            payload = json.loads(body) if body else {}
            result = handle.call(payload)
            self._reply(200, json.dumps({"result": result}).encode())
        except Exception as e:  # noqa: BLE001
            self._reply(500, json.dumps({"error": repr(e)}).encode())

    def do_POST(self):
        self._route()

    def do_GET(self):
        self._route()

    def do_PUT(self):
        self._route()

    def do_DELETE(self):
        self._route()

    def do_PATCH(self):
        self._route()


def start_http_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start the HTTP ingress; returns (server, (host, port)). POST
    /<deployment> with a JSON body routes to the deployment's __call__."""
    handler = type("_ProxyHandlerInstance", (_ProxyHandler,),
                   {"handles": {}, "asgi_flags": {}})
    server = ThreadingHTTPServer((host, port), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address
