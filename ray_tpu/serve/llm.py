"""Continuous-batching LLM serving engine on TPU.

The reference serves models via user code inside Serve replicas
(`python/ray/serve/_private/replica.py`, SURVEY.md P15) — it has no model
engine. This module is the TPU-native engine a Serve deployment wraps:

- **Continuous batching**: a fixed-shape decode program runs every step over
  all `max_batch` cache slots; which slots are live is a mask, so admitting
  or retiring a request never recompiles. New requests are prefilled into a
  free slot (prompt padded to a power-of-two bucket — a handful of compiled
  prefill variants total) while decode keeps streaming for everyone else.
- **Static shapes everywhere**: the only compiled programs are
  one decode step + one prefill per bucket size.
- Tokens stream back to callers through per-request queues; TTFT and
  throughput are measured at the engine so Serve autoscaling can act on
  queue depth and latency.

Threading: one engine thread owns the device loop (prefill/decode); callers
enqueue requests and read token queues — no JAX calls on caller threads.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import decoding
from ray_tpu.models.decoding import (KVCache, SamplingParams, lax_slice_row,
                                     lax_update_row)
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

# Per-request TTFT decomposition (metrics plane): every request's time to
# first token splits into queue_wait (submit -> prefill dispatch),
# prefill (dispatch -> device completion, stamped by the ready watcher),
# pipeline_stall (device completion -> the loop draining the firsts) and
# ship (the host copy of the first-token batch). The four stages sum to
# the observed TTFT exactly (see Request.breakdown). Series carry the
# hosting deployment + replica tags (from the serve replica context) so
# the controller's autoscaler and the dashboard can split per
# deployment/replica; engines outside serve tag deployment="-".
_STAGES = ("queue_wait", "prefill", "pipeline_stall", "ship")
_serve_hist = _metrics.histogram(
    "ray_tpu_serve_stage_s", "per-request serve TTFT stage latency",
    tag_keys=("stage", "deployment", "replica"))


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class Request:
    request_id: int
    prompt: np.ndarray                    # [P] int32
    max_new_tokens: int = 128
    temperature: float = 0.0
    eos_id: int | None = None
    # filled by the engine:
    out: "queue.Queue[int | None]" = field(default_factory=queue.Queue)
    submit_t: float = field(default_factory=time.monotonic)
    first_token_t: float | None = None
    # TTFT decomposition stamps (see Request.breakdown): prefill batch
    # dispatched / device results ready (watcher thread) / loop drained
    # the first-token batch to the host
    dispatch_t: float | None = None
    ready_t: float | None = None
    drain_t: float | None = None
    generated: int = 0
    slot: int = -1
    # set before the None sentinel when the request itself failed
    # (e.g. prompt longer than the cache) — distinguishes rejection from
    # a legitimate empty/EOS completion
    error: BaseException | None = None
    # tracing: the ambient span context at submit() (the replica's run
    # span when the request came through serve) plus a wall-clock submit
    # stamp — the engine emits its TTFT stage spans against these after
    # the first token drains
    trace_ctx: object | None = None
    submit_wall: float | None = None

    @property
    def ttft(self) -> float | None:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def breakdown(self) -> dict | None:
        """Measured TTFT decomposition. ``ready_t`` (stamped by the
        watcher thread off the device stream) is clamped into
        [dispatch_t, drain_t] so the four stages ALWAYS sum to the
        observed TTFT exactly."""
        if (self.first_token_t is None or self.dispatch_t is None
                or self.drain_t is None):
            return None
        ready = self.ready_t if self.ready_t is not None else self.drain_t
        ready = min(max(ready, self.dispatch_t), self.drain_t)
        return {
            "queue_wait_s": self.dispatch_t - self.submit_t,
            "prefill_s": ready - self.dispatch_t,
            "pipeline_stall_s": self.drain_t - ready,
            "ship_s": self.first_token_t - self.drain_t,
        }

    engine: "LLMEngine | None" = None

    def tokens(self) -> Iterator[int]:
        """Blocking stream of generated token ids (ends on None sentinel).
        Raises the engine's error if its device loop died."""
        while True:
            tok = self.out.get()
            if tok is None:
                if self.error is not None:
                    raise self.error
                if self.engine is not None and self.engine.error is not None:
                    raise RuntimeError(
                        "LLM engine loop failed"
                    ) from self.engine.error
                return
            yield tok


class LLMEngine:
    """Slot-based continuous batching over `ray_tpu.models.decoding`."""

    def __init__(self, cfg, params, *, max_batch: int = 8,
                 max_len: int = 2048, prefill_chunk: int = 1024,
                 decode_chunk: int | None = None,
                 drain_chunk: int | None = None):
        from ray_tpu.utils.config import get_config

        _cfg = get_config()
        self.cfg = cfg
        self.params = params
        self.max_batch = max_batch
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        # tokens generated per device round trip: one host sync per CHUNK
        # of decode steps (lax.scan), not per token — essential when the
        # chip sits behind a network tunnel where each sync costs an RTT,
        # and still fewer dispatches on local chips. Admission of waiting
        # requests happens between chunks (adds <= chunk * step_time to
        # queueing latency). Default: flag serve_decode_chunk.
        if decode_chunk is None:
            decode_chunk = _cfg.serve_decode_chunk
        self.decode_chunk = max(1, decode_chunk)
        self._drain_chunk_flag = (drain_chunk if drain_chunk is not None
                                  else _cfg.serve_drain_chunk)
        # serve replica identity: set by the hosting _Replica before it
        # constructs the deployment body; engines built outside serve
        # get a private tag (bench / direct use)
        from ray_tpu.serve.context import get_replica_context
        ctx = get_replica_context()
        self.deployment_name = ctx.deployment if ctx else "-"
        self.replica_tag = (ctx.replica_tag if ctx
                            else f"engine-{id(self) & 0xffffff:06x}")
        # continuous admission (flag serve_continuous_admission): the
        # loop opens a timed window between chunk dispatches so a
        # request arriving mid-chunk prefills behind ONE in-flight
        # chunk instead of waiting out the full double-buffered
        # pipeline (the dominant queue_wait term in BENCH_r07)
        self._continuous_admission = bool(_cfg.serve_continuous_admission)
        self._window_frac = min(0.95, max(
            0.0, float(_cfg.serve_admission_window_frac)))
        self._sync_t: float | None = None       # last chunk-sync finish
        self._chunk_period: float | None = None  # EMA between syncs
        # host-side slot state (mirrors cache.lengths but trusted copy)
        self._lengths = np.zeros((max_batch,), np.int32)
        self._last_tok = np.zeros((max_batch,), np.int32)
        # bumped per admission into a slot: lets the pipelined loop tell
        # "same slot, same request" from "same slot, NEW request" when
        # deciding whether an in-flight chunk's tokens are still valid
        self._slot_gen = np.zeros((max_batch,), np.int64)
        self._active: list[Request | None] = [None] * max_batch
        self._waiting: "queue.Queue[Request]" = queue.Queue()
        self._req_ids = itertools.count()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._key = jax.random.key(0)
        self.error: BaseException | None = None
        self._submit_lock = threading.Lock()
        # metrics (TTFT window is bounded: a long-lived replica must not
        # grow memory per request, and a recent window tracks current
        # latency better than an all-time mean)
        self.total_generated = 0
        self.total_finished = 0
        self.ttfts: "deque[float]" = deque(maxlen=1024)
        # per-request TTFT stage breakdowns (same bounded window)
        self.breakdowns: "deque[dict]" = deque(maxlen=1024)
        # pre-resolved per-(deployment, replica) stage-histogram handles
        self._h_stage = {s: _serve_hist.handle(
            {"stage": s, "deployment": self.deployment_name,
             "replica": self.replica_tag}) for s in _STAGES}
        # ready watcher: stamps Request.ready_t when a prefill batch's
        # device results complete — block_until_ready OFF the loop
        # thread, so the measurement never stalls the decode pipeline
        self._ready_q: "queue.Queue | None" = None
        if _metrics.enabled():
            self._ready_q = queue.Queue()
            threading.Thread(target=self._ready_watcher, daemon=True,
                             name="llm-ready-watcher").start()
        # device-resident loop inputs (see _device_inputs)
        self._dev_inputs: dict | None = None
        self._dev_dirty = True
        # device-resident last-token vector (chained through decode
        # programs and prefill scatters; see _dispatch_decode)
        self._last_dev = None
        self._scatter_fn = jax.jit(
            lambda last, slots, firsts:
            last.at[slots].set(firsts.astype(last.dtype)))
        # prefill batches whose first tokens haven't reached the host
        # yet: (dispatch_seq_at, items, firsts_device)
        self._pending_firsts: list = []
        self._dispatch_seq = 0
        # set when an admission failed on resources (not slots) this
        # round — gates the free-slot drain clause
        self._admission_blocked = False
        # drain-mode decode: a SHORT chunk used when a slot is about to
        # retire while requests wait, so admission happens within a few
        # steps instead of a full chunk (TTFT <- admission latency);
        # flag serve_drain_chunk
        self._drain_chunk = max(1, min(self._drain_chunk_flag,
                                       self.decode_chunk))
        self._setup_device_state()

    def _setup_device_state(self):
        """Build the KV cache + compiled programs (dense layout; the
        paged engine overrides this — serve/paged_llm.py)."""
        cfg = self.cfg
        self._cache = decoding.init_cache(cfg, self.max_batch,
                                          self.max_len)
        self._decode_fn = jax.jit(
            partial(self._decode_impl, cfg, chunk=self.decode_chunk),
            donate_argnums=(1,)
        )
        self._decode_fn_drain = (
            self._decode_fn if self._drain_chunk == self.decode_chunk
            else jax.jit(
                partial(self._decode_impl, cfg, chunk=self._drain_chunk),
                donate_argnums=(1,)))
        self._prefill_fn = jax.jit(
            partial(self._prefill_impl, cfg),
            static_argnames=("bucket",), donate_argnums=(1,),
        )
        # batched prefill: N prompts of one bucket in ONE dispatch —
        # through a network tunnel each dispatch costs ~an RTT, so a
        # 16-request burst admitted one-by-one pays 16 serial RTTs of
        # TTFT before any compute. Specializes per (n, bucket) shape;
        # admission splits bursts into power-of-two groups so the
        # variant count stays logarithmic.
        self._prefill_batch_fn = jax.jit(
            partial(self._prefill_batch_impl, cfg), donate_argnums=(1,))

    # -- jitted programs ---------------------------------------------------

    @staticmethod
    def _decode_impl(cfg, params, cache: KVCache, tokens, lengths, active,
                     temps, key, *, chunk):
        """``chunk`` decode steps over every slot in one compiled program
        (scan); returns the [chunk, max_batch] token matrix plus the
        advanced lengths (kept ON DEVICE so chained chunks never need a
        host upload). Inactive slots are computed but masked (position 0
        write is harmless: a later prefill overwrites). Slots finishing
        mid-chunk keep decoding; the host drops their surplus tokens."""
        def step(carry, _):
            cache, toks, lens, key = carry
            key, sub = jax.random.split(key)
            start = jnp.where(active, lens, 0)
            logits, cache = decoding.cached_forward(
                cfg, params, toks[:, None], cache, start=start,
                logits_mode="last",
            )
            nxt = decoding.select_tokens(logits, temps, sub)
            lens = jnp.where(active, lens + 1, lens)
            return (cache, nxt, lens, key), nxt

        (cache, _, lens, _), toks = jax.lax.scan(
            step, (cache, tokens, lengths, key), None, length=chunk)
        # merged last-token vector: chunk-active slots advance to their
        # newest token, others keep their prior value — the loop chains
        # every next dispatch off this DEVICE array, so admissions /
        # retirements never force a host round trip to rebuild last_tok
        new_last = jnp.where(active, toks[-1], tokens)
        return cache, toks, lens, new_last

    @staticmethod
    def _prefill_impl(cfg, params, cache: KVCache, tokens, plen, slot, *,
                      bucket):
        """Prefill one prompt (padded to `bucket`) into cache row `slot`.
        Operates on a sliced single-row cache so cost is independent of
        max_batch."""
        row_k = lax_slice_row(cache.k, slot)
        row_v = lax_slice_row(cache.v, slot)
        row = KVCache(k=row_k, v=row_v,
                      lengths=jnp.zeros((1,), jnp.int32))
        logits, row = decoding.cached_forward(
            cfg, params, tokens[None, :], row,
            start=jnp.zeros((1,), jnp.int32),
            logits_mode="index", logits_idx=plen[None] - 1,
        )
        k = lax_update_row(cache.k, row.k, slot)
        v = lax_update_row(cache.v, row.v, slot)
        return KVCache(k=k, v=v, lengths=cache.lengths), logits[0]

    @staticmethod
    def _prefill_batch_impl(cfg, params, cache: KVCache, tokens, plens,
                            slots, temps, key):
        """Prefill ``n`` prompts (one bucket, padded) into cache rows
        ``slots`` in a single program, and sample each row's first
        token. Rows are gathered, run as one batch-n forward, and
        scattered back — cost scales with n, dispatch overhead doesn't."""
        n = tokens.shape[0]
        rows = KVCache(
            k=jnp.take(cache.k, slots, axis=1),
            v=jnp.take(cache.v, slots, axis=1),
            lengths=jnp.zeros((n,), jnp.int32))
        logits, rows = decoding.cached_forward(
            cfg, params, tokens, rows,
            start=jnp.zeros((n,), jnp.int32),
            logits_mode="index", logits_idx=plens - 1,
        )
        k = cache.k.at[:, slots].set(rows.k.astype(cache.k.dtype))
        v = cache.v.at[:, slots].set(rows.v.astype(cache.v.dtype))
        first = decoding.select_tokens(logits, temps, key)
        return KVCache(k=k, v=v, lengths=cache.lengths), first

    def warmup(self, prompt_len: int):
        """Deterministically compile every program a burst at this
        prompt bucket can hit: the batched prefill at each power-of-two
        group size up to max_batch, and both decode programs. Call
        BEFORE start() (request-driven warmup races the admit loop, so
        which (n, bucket) prefill variants compile is scheduling-
        dependent — a missed one lands seconds of JIT inside a measured
        or user-facing TTFT)."""
        bucket = min(_bucket(prompt_len), self.max_len)
        tokens = jnp.zeros((1, bucket), jnp.int32)
        if self._last_dev is None:
            self._last_dev = jnp.asarray(self._last_tok)
        n = 1
        while n <= self.max_batch:
            toks = jnp.broadcast_to(tokens, (n, bucket))
            self._cache, firsts = self._prefill_batch_fn(
                self.params, self._cache, toks,
                jnp.ones((n,), jnp.int32),
                jnp.arange(n, dtype=jnp.int32),
                jnp.zeros((n,), jnp.float32), self._next_key())
            # warm the firsts scatter at this group size too: it
            # specializes per slots-shape, and a compile inside _admit
            # stalls the loop ~0.5s per NEW burst size (measured)
            self._last_dev = self._scatter_fn(
                self._last_dev, jnp.arange(n, dtype=jnp.int32), firsts)
            np.asarray(firsts)
            n *= 2
        self._last_dev = jnp.asarray(self._last_tok)
        active = jnp.zeros((self.max_batch,), bool)
        for fn in {id(self._decode_fn): self._decode_fn,
                   id(self._decode_fn_drain):
                       self._decode_fn_drain}.values():
            self._cache, toks, _, _ = fn(
                self.params, self._cache,
                jnp.zeros((self.max_batch,), jnp.int32),
                jnp.zeros((self.max_batch,), jnp.int32), active,
                jnp.zeros((self.max_batch,), jnp.float32),
                self._next_key())
            np.asarray(toks)
        # warmup wrote garbage prefills into cache rows; lengths stayed
        # 0 and no slot is active, so real admissions overwrite cleanly
        self._lengths[:] = 0
        self._last_tok[:] = 0

    # -- engine loop -------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._ready_q is not None:
            self._ready_q.put(None)

    def _ready_watcher(self):
        """Stamp ready_t per prefill batch in dispatch order (device
        stream order, so sequential blocking gives correct stamps)."""
        while True:
            item = self._ready_q.get()
            if item is None:
                return
            firsts, reqs = item
            try:
                firsts.block_until_ready()
            except Exception:  # noqa: BLE001 - backend quirk: skip stamp
                continue
            now = time.monotonic()
            for r in reqs:
                if r.ready_t is None:
                    r.ready_t = now

    def submit(self, prompt, *, max_new_tokens: int = 128,
               temperature: float = 0.0, eos_id: int | None = None) -> Request:
        req = Request(
            request_id=next(self._req_ids),
            prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            eos_id=eos_id,
        )
        req.engine = self
        if _tracing.is_enabled():
            req.trace_ctx = _tracing.current_context()
            req.submit_wall = time.time()
        # Lock pairs with the drain in _loop's finally: a request either
        # lands in _waiting before the drain (and gets its sentinel
        # there) or observes the dead/stopped engine here — never neither.
        with self._submit_lock:
            if self.error is not None or self._stop.is_set():
                req.out.put(None)  # engine is dead: fail fast at tokens()
            else:
                self._waiting.put(req)
        return req

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._active) if r is None]

    def _on_slot_retired(self, slot: int):
        """Hook: a request finished and its slot was released (paged
        engine reclaims KV pages here)."""
        self._dev_dirty = True

    def _on_idle(self):
        """Hook: the loop has no active slots and nothing in flight
        (paged engine finishes deferred page frees here — with the
        pipeline drained they cannot race an in-flight chunk)."""

    def _reserve_slot_resources(self, req: "Request", slot: int) -> bool:
        """Hook: claim per-slot resources for an admission (paged engine
        reserves KV pages). False = backpressure — the caller requeues
        the request and stops admitting this round."""
        return True

    def _pack_admit(self, req: "Request", slot: int, plen: int) -> tuple:
        """Hook: build one admit item (req, slot, plen, padded) — the
        tokens the prefill program must actually process, padded to a
        power-of-two bucket (the paged engine packs only the
        non-prefix-cached SUFFIX here)."""
        bucket = min(_bucket(plen), self.max_len)
        padded = np.zeros((bucket,), np.int32)
        padded[:plen] = req.prompt
        return (req, slot, plen, padded)

    def _dispatch_prefill(self, part: list, bucket: int):
        """Hook: dispatch one prefill sub-batch (``part`` is a list of
        (req, slot, plen, padded)); returns the device first-tokens."""
        tokens = jnp.asarray(np.stack([it[3] for it in part]))
        plens = jnp.asarray(np.array([it[2] for it in part], np.int32))
        slots = jnp.asarray(np.array([it[1] for it in part], np.int32))
        temps = jnp.asarray(np.array(
            [it[0].temperature for it in part], np.float32))
        self._cache, firsts = self._prefill_batch_fn(
            self.params, self._cache, tokens, plens, slots, temps,
            self._next_key(),
        )
        return firsts

    def _admit(self, first: "Request | None" = None):
        """Prefill waiting requests into free slots. All prefills of the
        round are DISPATCHED first and their first tokens extracted in
        one host pass — through a network tunnel the per-sync RTT is the
        dominant prefill cost, so a burst of admissions pays ~one RTT,
        not one per request. ``first``: a request already pulled off the
        queue (the admission window's timed get) — admitted ahead of the
        queue, requeued on backpressure like any other."""
        admits = []   # (req, slot, plen, padded)
        self._admission_blocked = False
        pulled = first
        for slot in self._free_slots():
            if pulled is not None:
                req, pulled = pulled, None
            else:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    break
            plen = len(req.prompt)
            if plen >= self.max_len:
                req.error = ValueError(
                    f"prompt length {plen} >= engine max_len "
                    f"{self.max_len}")
                req.out.put(None)
                continue
            if not self._reserve_slot_resources(req, slot):
                if req.error is not None:
                    # permanently infeasible (e.g. a reservation larger
                    # than the whole page pool): reject — requeueing
                    # would hang it and head-of-line-block the queue
                    req.out.put(None)
                    continue
                self._waiting.put(req)   # backpressure: retry later
                self._admission_blocked = True
                break
            admits.append(self._pack_admit(req, slot, plen))
        if pulled is not None:
            self._waiting.put(pulled)   # no free slot took it
        if not admits:
            return
        # Group by bucket, then split each group into POWER-OF-TWO
        # sub-batches: one batched-prefill dispatch per sub-batch (a
        # 16-burst = 1 dispatch; 15 = 8+4+2+1 = 4) with one stacked
        # prompt upload each. Per-dispatch tunnel RTTs would otherwise
        # dominate burst TTFT.
        groups: dict[int, list] = {}
        for item in admits:
            groups.setdefault(len(item[3]), []).append(item)
        batches = []   # (items, first_tokens_device)
        for bucket, items in groups.items():
            i = 0
            while i < len(items):
                m = 1
                while m * 2 <= len(items) - i:
                    m *= 2
                part = items[i:i + m]
                i += m
                firsts = self._dispatch_prefill(part, bucket)
                now = time.monotonic()
                for it in part:
                    it[0].dispatch_t = now
                if self._ready_q is not None:
                    self._ready_q.put((firsts, [it[0] for it in part]))
                batches.append((part, firsts))
        # ASYNC first tokens: scatter each batch's firsts into the
        # device last-token vector (so the very next decode chunk
        # covers the new slots with no host round trip) and activate
        # the slots NOW; the host-side emission of the first tokens
        # happens in _drain_firsts when the async copy lands. Blocking
        # here for the sync RTT stalled the whole decode pipeline once
        # per admission round — with small chunks that stall WAS the
        # sustained-TTFT/throughput ceiling.
        for part, firsts in batches:
            slots = jnp.asarray(np.array([it[1] for it in part],
                                         np.int32))
            self._last_dev = self._scatter_fn(self._last_dev, slots,
                                              firsts)
            try:
                firsts.copy_to_host_async()
            except Exception:  # noqa: BLE001 - backend without async copy
                pass
            for (req, slot, plen, _) in part:
                req.slot = slot
                self._active[slot] = req
                # admission GENERATION: an in-flight decode chunk
                # dispatched for this slot's PREVIOUS occupant must
                # neither have its tokens emitted to the new request
                # nor be chained from
                self._slot_gen[slot] += 1
                self._lengths[slot] = plen
            # any chunk dispatched from here on (seq >= _dispatch_seq)
            # executes after this prefill on the device stream
            self._pending_firsts.append(
                (self._dispatch_seq, part, firsts))
        self._dev_dirty = True   # active set / lengths changed

    def _drain_firsts(self, completed_seq: int | None = None):
        """Emit first tokens whose prefill results reached the host.
        ``completed_seq``: a decode chunk with this dispatch seq has
        been READ on the host — every prefill dispatched before it is
        device-complete, so blocking on those firsts costs only the
        (already overlapped) copy."""
        if not self._pending_firsts:
            return
        keep = []
        for seq_at, part, firsts in self._pending_firsts:
            # NOTE: no is_ready() polling — on tunneled backends the
            # readiness query is itself a blocking RTT, which (measured)
            # serialized the whole loop. Readiness is derived purely
            # from device-stream ordering via completed_seq.
            if completed_seq is None or seq_at > completed_seq:
                keep.append((seq_at, part, firsts))
                continue
            t_drain = time.monotonic()
            vals = np.asarray(firsts)
            now = time.monotonic()
            for (req, slot, plen, _), first in zip(part, vals):
                req.drain_t = t_drain
                req.first_token_t = now
                self.ttfts.append(req.ttft)
                bd = req.breakdown
                if bd is not None:
                    self.breakdowns.append(bd)
                    if _metrics.enabled():
                        for stage in _STAGES:
                            self._h_stage[stage].observe(bd[f"{stage}_s"])
                    if req.trace_ctx is not None \
                            and req.submit_wall is not None:
                        self._emit_trace_spans(req, bd)
                self._emit(req, int(first))
        self._pending_firsts = keep

    def _emit_trace_spans(self, req: Request, bd: dict):
        """The engine's span subtree for one traced request: an
        ``engine.request`` parent spanning submit -> first token
        (wall-anchored at the submit stamp, parented to the replica's
        run span), with the four TTFT stages as SEQUENTIAL children.
        ``breakdown`` clamps the stamps, so the children tile the parent
        exactly — the waterfall shows queue_wait/prefill/pipeline_stall/
        ship summing to the traced TTFT."""
        ttft = req.ttft
        if ttft is None:
            return
        parent = _tracing.emit(
            "engine.request", start=req.submit_wall, duration=ttft,
            parent=req.trace_ctx, kind="serve",
            attrs={"request_id": req.request_id,
                   "deployment": self.deployment_name,
                   "replica": self.replica_tag})
        t = req.submit_wall
        for stage in _STAGES:
            d = bd[f"{stage}_s"]
            _tracing.emit(f"engine.{stage}", start=t, duration=d,
                          parent=parent, kind="serve")
            t += d

    def _admission_window(self) -> bool:
        """Continuous admission: between the previous chunk's sync and
        the NEXT chunk's dispatch, block on the waiting queue for up to
        a fraction of the EMA chunk period and prefill arrivals
        immediately. A prefill dispatched here queues behind only the
        ONE in-flight chunk — without the window, a request arriving
        just after an emit waits out the whole double-buffered pipeline
        (~2.5 chunks of queue_wait, the dominant TTFT term in
        BENCH_r07). The wait costs no device time: the in-flight chunk
        computes while this thread sleeps, and the remaining period
        fraction covers the next dispatch. Skipped until the loop has a
        period estimate, when no slot is free, or under page
        backpressure (a request the pool can't place would spin)."""
        if (not self._continuous_admission or self._chunk_period is None
                or self._sync_t is None):
            return False
        deadline = self._sync_t + self._window_frac * self._chunk_period
        admitted = False
        while not self._stop.is_set():
            if self._admission_blocked or \
                    not any(r is None for r in self._active):
                break
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                break
            try:
                req = self._waiting.get(timeout=timeout)
            except queue.Empty:
                break
            self._admit(first=req)
            admitted = True
        return admitted

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _emit(self, req: Request, tok: int):
        req.generated += 1
        self.total_generated += 1
        self._last_tok[req.slot] = tok
        # the cache-capacity cutoff counts prompt + emitted tokens — the
        # _lengths mirror is chunk-granular (pre-advanced at dispatch)
        # and would trip this up to two chunks early
        done = (req.eos_id is not None and tok == req.eos_id) or \
            req.generated >= req.max_new_tokens or \
            len(req.prompt) + req.generated >= self.max_len
        req.out.put(tok)
        if done:
            req.out.put(None)
            self._active[req.slot] = None
            self.total_finished += 1
            self._on_slot_retired(req.slot)
        else:
            # the emitted token occupies position lengths[slot] next step
            pass

    def _loop(self):
        try:
            self._run_loop()
        except BaseException as e:  # noqa: BLE001 — propagate to callers
            self.error = e
        finally:
            # Runs on BOTH error and clean stop(): every live stream and
            # every waiter gets its sentinel, so no tokens() consumer can
            # hang. Under _submit_lock so no request slips in after the
            # drain (see submit()).
            with self._submit_lock:
                self._stop.set()
                for req in self._active:
                    if req is not None:
                        req.out.put(None)
                while True:
                    try:
                        self._waiting.get_nowait().out.put(None)
                    except queue.Empty:
                        break

    def _use_drain_chunk(self) -> bool:
        """Short decode chunks ONLY when a waiting request could
        actually be admitted soon — i.e. a slot is about to retire (an
        active request near its token budget). Draining whenever the
        queue was non-empty ran 4-step chunks for entire saturated runs
        (4x the sync overhead) while no slot could possibly free.

        Two admission opportunities count: a FREE SLOT already exists
        (run the engine with max_batch above the offered concurrency and
        this is the common case — admission then never waits for a
        retirement), or a retirement is imminent. The horizon is 3
        chunks because the double-buffered loop's ``generated`` counts
        lag the device by up to two in-flight chunks."""
        if self._waiting.empty():
            return False
        if any(r is None for r in self._active) \
                and not self._admission_blocked:
            # a free slot AND admission actually possible (a page-starved
            # paged engine must not drain forever against a free slot it
            # cannot fill)
            return True
        horizon = 3 * self.decode_chunk
        return any(
            r is not None
            and (r.max_new_tokens - r.generated) <= horizon
            for r in self._active)

    def _device_inputs(self, active_idx):
        """Device-resident loop inputs (active mask, temps, lengths).
        Uploaded only when admission/retirement changed them — through a
        remote-device tunnel each per-dispatch host upload costs an RTT
        that would otherwise serialize with the decode chunks."""
        if self._dev_inputs is None or self._dev_dirty:
            active = np.zeros((self.max_batch,), bool)
            active[active_idx] = True
            temps = np.array(
                [r.temperature if r is not None else 0.0
                 for r in self._active], np.float32)
            self._dev_inputs = {
                "active": jnp.asarray(active),
                "temps": jnp.asarray(temps),
                # .copy(): the host mirror is mutated right after each
                # dispatch; an asynchronous transfer reading the live
                # buffer would upload a torn lengths vector
                "lens": jnp.asarray(self._lengths.copy()),
            }
            self._dev_dirty = False
        return self._dev_inputs

    def _decode_call(self, chunk: int, last_tok, dev):
        """Hook: run the compiled decode program for one chunk and
        return (token_matrix, advanced_lens, merged_last_tok) — the
        ONLY piece the paged engine overrides; the pipeline tail below
        stays shared."""
        decode = (self._decode_fn_drain if chunk == self._drain_chunk
                  and self._decode_fn_drain is not self._decode_fn
                  else self._decode_fn)
        self._cache, toks, lens, new_last = decode(
            self.params, self._cache, last_tok,
            dev["lens"], dev["active"], dev["temps"], self._next_key(),
        )
        return toks, lens, new_last

    def _dispatch_decode(self, active_idx):
        """Dispatch one decode chunk (no host sync), chained off the
        DEVICE-resident last-token vector — admissions (prefill firsts
        scattered into it) and chunk outputs (merged in the decode
        program) both update it on device, so consecutive dispatches
        never need a host round trip no matter how the active set
        changed in between."""
        drain = self._use_drain_chunk()
        chunk = self._drain_chunk if drain else self.decode_chunk
        dev = self._device_inputs(active_idx)
        toks, lens, new_last = self._decode_call(chunk, self._last_dev,
                                                 dev)
        self._last_dev = new_last
        dev["lens"] = lens   # stays on device for the chained chunk
        # start the token matrix's device->host copy NOW: it overlaps
        # the next chunk's compute instead of adding a serial RTT to
        # every chunk sync
        try:
            toks.copy_to_host_async()
        except Exception:  # noqa: BLE001 - backend without async copy
            pass
        # host mirror advances deterministically (+chunk per active
        # slot) — retired slots are reconciled at admission
        self._lengths[active_idx] += chunk
        gens = [int(self._slot_gen[i]) for i in active_idx]
        seq = self._dispatch_seq
        self._dispatch_seq += 1
        return toks, active_idx, gens, chunk, seq

    def _emit_chunk(self, toks_np, active_idx, gens):
        for i, gen in zip(active_idx, gens):
            if self._slot_gen[i] != gen:
                continue   # slot re-admitted since dispatch: the chunk's
                # tokens belong to the RETIRED occupant, not this request
            for t in range(toks_np.shape[0]):
                req = self._active[i]
                if req is None:
                    break   # finished mid-chunk; drop surplus tokens
                self._emit(req, int(toks_np[t, i]))

    def _run_loop(self):
        """Double-buffered decode over a device-resident last-token
        vector: while chunk N's tokens copy back to the host and get
        emitted, chunk N+1 already runs on device. Admissions scatter
        their (still on-device) first tokens into the vector, so the
        pipeline NEVER stalls for a prefill sync — first tokens are
        emitted asynchronously when their copy lands (_drain_firsts).
        Emission order per request is preserved: firsts dispatched
        before chunk N are force-drained right after chunk N's sync,
        before the chunk's tokens are emitted."""
        pending = None   # (device_toks, active_idx, gens, chunk, seq)
        self._last_dev = jnp.asarray(self._last_tok)
        while not self._stop.is_set():
            self._admit()
            active_idx = [i for i, r in enumerate(self._active)
                          if r is not None]
            if not active_idx:
                self._sync_t = None   # pipeline drains: period resets
                if pending is not None:
                    toks, idxs, gens, _, seq = pending
                    pending = None
                    toks_np = np.asarray(toks)
                    self._drain_firsts(completed_seq=seq)
                    self._emit_chunk(toks_np, idxs, gens)
                    continue
                if self._pending_firsts:
                    # every active request is brand-new and nothing is
                    # in flight (e.g. max_new_tokens=1 bursts): block
                    # for the outstanding firsts
                    self._drain_firsts(completed_seq=self._dispatch_seq)
                    continue
                self._on_idle()
                time.sleep(0.001)
                continue
            if pending is None:
                pending = self._dispatch_decode(active_idx)
                continue
            # continuous admission: requests arriving while `pending`
            # computes are prefilled NOW, before the next chunk is
            # dispatched behind them
            if self._admission_window():
                active_idx = [i for i, r in enumerate(self._active)
                              if r is not None]
            nxt = self._dispatch_decode(active_idx)
            toks_prev, idx_prev, gens_prev, _, seq_prev = pending
            # EVERY pending prefill was dispatched before nxt: block for
            # their firsts now (bounded by chunk N + prefill compute —
            # chunk N+1 is already queued behind them, so this wait
            # steals no device time) and emit them FIRST. Waiting for
            # the next chunk's sync instead cost a whole extra chunk of
            # first-token latency.
            self._drain_firsts(completed_seq=self._dispatch_seq)
            toks_np = np.asarray(toks_prev)     # chunk N host sync
            now = time.monotonic()
            if self._sync_t is not None:
                period = now - self._sync_t
                self._chunk_period = (
                    period if self._chunk_period is None
                    else 0.5 * self._chunk_period + 0.5 * period)
            self._sync_t = now
            self._emit_chunk(toks_np, idx_prev, gens_prev)
            pending = nxt

    # -- metrics -----------------------------------------------------------

    def stats(self) -> dict:
        live = sum(r is not None for r in self._active)
        out = {
            "active_slots": live,
            "waiting": self._waiting.qsize(),
            "total_generated": self.total_generated,
            "total_finished": self.total_finished,
            "mean_ttft_s": float(np.mean(self.ttfts)) if self.ttfts else None,
        }
        if self.breakdowns:
            bs = list(self.breakdowns)
            out["ttft_breakdown_s"] = {
                k: float(np.mean([b[k] for b in bs]))
                for k in ("queue_wait_s", "prefill_s",
                          "pipeline_stall_s", "ship_s")}
            total = sum(out["ttft_breakdown_s"].values())
            if total > 0:
                out["queue_wait_share"] = (
                    out["ttft_breakdown_s"]["queue_wait_s"] / total)
        return out


class LLMDeployment:
    """Serve deployment body hosting an LLMEngine in the replica process.

    Use with ``@serve.deployment``/`serve.run`; each replica owns its own
    engine (and TPU chip(s)). `model_builder` is a picklable zero-arg
    callable returning (cfg, params) — keeps weights out of the deploy RPC.

        dep = serve.deployment(LLMDeployment).bind(model_builder=build)
        handle = serve.run(dep)
        tokens = handle.remote([1, 2, 3], max_new_tokens=16).result()
    """

    def __init__(self, model_builder, *, max_batch: int = 8,
                 max_len: int = 2048, kv_layout: str = "paged",
                 **engine_kwargs):
        cfg, params = model_builder()
        if kv_layout == "paged":
            from ray_tpu.serve.paged_llm import PagedLLMEngine

            self._engine = PagedLLMEngine(
                cfg, params, max_batch=max_batch, max_len=max_len,
                **engine_kwargs)
        elif kv_layout == "dense":
            self._engine = LLMEngine(cfg, params, max_batch=max_batch,
                                     max_len=max_len, **engine_kwargs)
        else:
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        self._engine.start()

    def __call__(self, prompt, max_new_tokens: int = 128,
                 temperature: float = 0.0, eos_id: int | None = None):
        req = self._engine.submit(
            prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, eos_id=eos_id)
        return list(req.tokens())

    def stats(self) -> dict:
        return self._engine.stats()


