"""Declarative Serve config: build/apply deployments from dict or YAML.

Reference analog: ``serve/schema.py`` (ServeDeploySchema /
ServeApplicationSchema pydantic models behind the REST config and the
``serve deploy config.yaml`` CLI). Shape:

.. code-block:: yaml

    applications:
      - name: app1
        deployments:
          - name: Summarizer            # optional override
            import_path: my_module:summarizer   # a Deployment object
            num_replicas: 2
            init_args: ["en"]
            init_kwargs: {beam: 4}
            user_config: {temperature: 0.2}
            max_concurrent_queries: 16
            autoscaling_config: {min_replicas: 1, max_replicas: 4}

``import_path`` is ``module:attr`` or ``module.attr`` resolving to a
``Deployment`` (bound or not). ``apply_config`` deploys every entry and
returns {deployment_name: DeploymentHandle}. Init-arg layering:
``init_args`` in the config REPLACES the target's bound positionals
when present (otherwise they are kept), and ``init_kwargs`` MERGES over
the target's bound kwargs key by key. The whole config is built and
validated before anything deploys, so a config error (bad import path,
unknown field, name collision) in any entry leaves nothing running; a
RUNTIME failure while deploying entry N (replica init raising,
resources never scheduling) can still leave entries before it live —
the controller keeps them and the raised error names the failed entry.
Validation errors name the offending field —
there is no pydantic in the image, so a small hand validator plays that
role.
"""

from __future__ import annotations

import importlib

from ray_tpu.serve import api as _api

_DEPLOYMENT_FIELDS = {
    "name", "import_path", "num_replicas", "init_args", "init_kwargs",
    "user_config", "max_concurrent_queries", "autoscaling_config",
    "resources_per_replica",
}


def import_attr(path: str):
    """Resolve ``module:attr`` (preferred) or dotted ``module.attr``."""
    if ":" in path:
        mod_name, _, attr = path.partition(":")
    else:
        mod_name, _, attr = path.rpartition(".")
    if not mod_name or not attr:
        raise ValueError(f"malformed import_path {path!r} "
                         "(want module:attr)")
    mod = importlib.import_module(mod_name)
    try:
        return getattr(mod, attr)
    except AttributeError:
        raise ValueError(
            f"import_path {path!r}: module {mod_name!r} has no "
            f"attribute {attr!r}") from None


def _validate_deployment(spec: dict, where: str):
    if not isinstance(spec, dict):
        raise ValueError(f"{where}: deployment entry must be a mapping")
    unknown = set(spec) - _DEPLOYMENT_FIELDS
    if unknown:
        raise ValueError(
            f"{where}: unknown field(s) {sorted(unknown)}; "
            f"allowed: {sorted(_DEPLOYMENT_FIELDS)}")
    if "import_path" not in spec:
        raise ValueError(f"{where}: import_path is required")


def _build_one(spec: dict, where: str) -> "_api.Deployment":
    _validate_deployment(spec, where)
    target = import_attr(spec["import_path"])
    if not isinstance(target, _api.Deployment):
        raise ValueError(
            f"{where}: {spec['import_path']!r} resolved to "
            f"{type(target).__name__}, expected a @serve.deployment")
    auto = spec.get("autoscaling_config")
    if isinstance(auto, dict):
        from ray_tpu.serve.config import AutoscalingConfig

        try:
            auto = AutoscalingConfig(**auto)
        except TypeError as e:
            raise ValueError(f"{where}: bad autoscaling_config: {e}") \
                from None
    dep = target.options(
        name=spec.get("name"),
        num_replicas=spec.get("num_replicas"),
        max_concurrent_queries=spec.get("max_concurrent_queries"),
        autoscaling_config=auto,
        user_config=spec.get("user_config"),
        resources_per_replica=spec.get("resources_per_replica"),
    )
    if "init_args" in spec or "init_kwargs" in spec:
        # config args layer over whatever the import target bound:
        # init_args replaces positionals only when present; init_kwargs
        # merges over bound kwargs
        args = spec.get("init_args", target._init_args)
        kwargs = {**target._init_kwargs, **spec.get("init_kwargs", {})}
        dep = dep.bind(*args, **kwargs)
    return dep


def apply_config(config: dict) -> dict:
    """Deploy every deployment in a config dict; returns
    {deployment_name: handle}. Accepts either the full two-level
    ``{"applications": [{"deployments": [...]}]}`` schema or a flat
    ``{"deployments": [...]}``."""
    if not isinstance(config, dict):
        raise ValueError("serve config must be a mapping")
    unknown = set(config) - {"applications", "deployments"}
    if unknown:
        raise ValueError(
            f"unknown top-level field(s) {sorted(unknown)}; expected "
            "'applications' or 'deployments'")
    apps = config.get("applications")
    if apps is None:
        if "deployments" not in config:
            raise ValueError(
                "config must contain 'applications' or 'deployments'")
        apps = [{"name": "default", "deployments":
                 config.get("deployments", [])}]
    # Phase 1: build + validate EVERYTHING (imports, fields, name
    # collisions) before any deployment goes live, so a CONFIG error in
    # entry N cannot leave entries 0..N-1 running. (Runtime deploy
    # failures in phase 2 are not rolled back — see module docstring.)
    built: list = []
    owner: dict = {}   # deployment name -> application that declared it
    for ai, app in enumerate(apps):
        if not isinstance(app, dict) or "deployments" not in app:
            raise ValueError(
                f"applications[{ai}]: expected a mapping with a "
                "'deployments' list")
        app_name = app.get("name", f"applications[{ai}]")
        for di, spec in enumerate(app["deployments"]):
            where = (f"applications[{ai}].deployments[{di}]"
                     if "applications" in config else f"deployments[{di}]")
            dep = _build_one(spec, where)
            if dep.name in owner:
                # deployment names are cluster-global here: a second app
                # reusing one would silently clobber the first
                raise ValueError(
                    f"{where}: deployment name {dep.name!r} already "
                    f"declared by {owner[dep.name]!r}; rename one "
                    "(names are global)")
            owner[dep.name] = app_name
            built.append(dep)
    # Phase 2: deploy
    return {dep.name: _api.run(dep) for dep in built}


def apply_config_file(path: str) -> dict:
    """YAML (or JSON — YAML is a superset) config file → apply_config."""
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f)
    return apply_config(config)
