"""Serve controller: declarative deployment state reconciliation.

Reference analog: ``serve/_private/controller.py`` (``ServeController:87``,
``run_control_loop:312``) + ``deployment_state.py`` (``DeploymentState
:1149`` — diff target vs actual replica sets) + autoscaling policy
(``_private/autoscaling_policy.py``). The controller is a named actor; a
background thread reconciles desired replica counts and drives
autoscaling from replica queue metrics.
"""

from __future__ import annotations

import threading
import time

import ray_tpu


class _Replica:
    """Replica actor body: wraps the user's deployment class.

    Reference analog: ``serve/_private/replica.py`` — handle_request:227.
    Requests run on the actor's concurrency pool; ``num_ongoing`` feeds
    both the router's p2c choice and controller autoscaling."""

    def __init__(self, cls_blob, init_args, init_kwargs, user_config):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        self._instance = cls(*init_args, **init_kwargs)
        if user_config and hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0

    def handle_request(self, method_name, args, kwargs):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target = (self._instance if method_name == "__call__"
                      else getattr(self._instance, method_name))
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def reconfigure(self, user_config):
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
        return True

    def metrics(self):
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total}

    def ping(self):
        return True


class ServeController:
    """Named actor ('SERVE_CONTROLLER'). Deployment lifecycle + replica
    sets + autoscaling."""

    def __init__(self):
        self._deployments: dict[str, dict] = {}
        self._lock = threading.RLock()
        self._stop = False
        self._version = 0
        self._loop = threading.Thread(target=self._control_loop, daemon=True)
        self._loop.start()

    # -- deployment API --------------------------------------------------
    def deploy(self, name: str, cls_blob: bytes, init_args, init_kwargs,
               config: dict):
        with self._lock:
            prev = self._deployments.get(name)
            self._deployments[name] = {
                "cls_blob": cls_blob,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "config": config,
                "replicas": prev["replicas"] if prev else [],
                "target": (config.get("autoscaling") or {}).get(
                    "min_replicas", config.get("num_replicas", 1))
                if config.get("autoscaling")
                else config.get("num_replicas", 1),
                "last_scale": time.monotonic(),
                "redeploy": prev is not None,
            }
            self._version += 1
        return True

    def delete_deployment(self, name: str):
        with self._lock:
            dep = self._deployments.pop(name, None)
            self._version += 1
        if dep:
            for r in dep["replicas"]:
                _kill_quietly(r)
        return True

    def get_replicas(self, name: str):
        """(version, [replica handles]) — handles are routable actor refs."""
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return self._version, None
            return self._version, list(dep["replicas"])

    def version(self) -> int:
        return self._version

    def list_deployments(self):
        with self._lock:
            return {
                name: {"target": dep["target"],
                       "running": len(dep["replicas"]),
                       "config": dep["config"]}
                for name, dep in self._deployments.items()
            }

    def shutdown(self):
        self._stop = True
        with self._lock:
            deps = list(self._deployments.values())
            self._deployments.clear()
        for dep in deps:
            for r in dep["replicas"]:
                _kill_quietly(r)
        return True

    # -- reconciliation --------------------------------------------------
    def _control_loop(self):
        while not self._stop:
            try:
                self._reconcile_once()
                self._autoscale_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                pass
            time.sleep(0.1)

    def _reconcile_once(self):
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            if dep.get("redeploy"):
                # config/code changed: replace replica set (reference:
                # rolling update; v1 does stop-then-start)
                old = dep["replicas"]
                dep["replicas"] = []
                dep["redeploy"] = False
                for r in old:
                    _kill_quietly(r)
                with self._lock:
                    self._version += 1
            target = dep["target"]
            replicas = dep["replicas"]
            while len(replicas) < target:
                replica_cls = ray_tpu.remote(_Replica)
                res = dep["config"].get("resources_per_replica") or {}
                opts = {"max_concurrency":
                        dep["config"].get("max_concurrent_queries", 8)}
                if res.get("CPU"):
                    opts["num_cpus"] = res["CPU"]
                if res.get("TPU"):
                    opts["num_tpus"] = res["TPU"]
                handle = replica_cls.options(**opts).remote(
                    dep["cls_blob"], dep["init_args"], dep["init_kwargs"],
                    dep["config"].get("user_config") or {})
                replicas.append(handle)
                with self._lock:
                    self._version += 1
            while len(replicas) > target:
                victim = replicas.pop()
                _kill_quietly(victim)
                with self._lock:
                    self._version += 1

    def _autoscale_once(self):
        now = time.monotonic()
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            auto = dep["config"].get("autoscaling")
            if not auto or not dep["replicas"]:
                continue
            try:
                metrics = ray_tpu.get(
                    [r.metrics.remote() for r in dep["replicas"]],
                    timeout=5)
            except Exception:  # noqa: BLE001
                continue
            ongoing = sum(m["ongoing"] for m in metrics)
            per_replica = ongoing / max(1, len(dep["replicas"]))
            target_per = auto.get("target_ongoing_requests", 2.0)
            if (per_replica > target_per
                    and dep["target"] < auto.get("max_replicas", 4)
                    and now - dep["last_scale"] > auto.get(
                        "upscale_delay_s", 0.5)):
                dep["target"] += 1
                dep["last_scale"] = now
            elif (per_replica < target_per / 2
                    and dep["target"] > auto.get("min_replicas", 1)
                    and now - dep["last_scale"] > auto.get(
                        "downscale_delay_s", 2.0)):
                dep["target"] -= 1
                dep["last_scale"] = now


def _kill_quietly(handle):
    try:
        ray_tpu.kill(handle)
    except Exception:  # noqa: BLE001
        pass
