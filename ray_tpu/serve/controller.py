"""Serve controller: declarative deployment state reconciliation.

Reference analog: ``serve/_private/controller.py`` (``ServeController:87``,
``run_control_loop:312``) + ``deployment_state.py`` (``DeploymentState
:1149`` — diff target vs actual replica sets) + autoscaling policy
(``_private/autoscaling_policy.py``). The controller is a named actor; a
background thread reconciles desired replica counts and drives
autoscaling from replica queue metrics.
"""

from __future__ import annotations

import threading
import time

import ray_tpu


class _Replica:
    """Replica actor body: wraps the user's deployment class.

    Reference analog: ``serve/_private/replica.py`` — handle_request:227.
    Requests run on the actor's concurrency pool; ``num_ongoing`` feeds
    both the router's p2c choice and controller autoscaling."""

    def __init__(self, cls_blob, init_args, init_kwargs, user_config,
                 deployment=None, replica_tag=None):
        import cloudpickle

        from ray_tpu.serve.context import set_replica_context

        self._deployment = deployment or "-"
        self._tag = replica_tag or f"replica-{id(self) & 0xffffff:06x}"
        # context must be installed on THIS thread before the user class
        # constructs: engines read it in __init__ to tag their metrics
        # series and prefix digests
        set_replica_context(self._deployment, self._tag)
        cls = cloudpickle.loads(cls_blob)
        init_args = [self._resolve_refs(a) for a in init_args]
        init_kwargs = {k: self._resolve_refs(v)
                       for k, v in init_kwargs.items()}
        self._instance = cls(*init_args, **init_kwargs)
        if user_config and hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0
        self._streams: dict = {}
        self._stream_errors: dict = {}
        # pushed ongoing gauge: the metrics-driven autoscaler consumes
        # this instead of polling each replica's metrics() every tick
        from ray_tpu.util import metrics as _metrics
        self._g_ongoing = (_metrics.gauge(
            "ray_tpu_serve_ongoing", "in-flight requests per replica",
            tag_keys=("deployment", "replica"))
            if _metrics.enabled() else None)
        self._set_ongoing_gauge()

    def _set_ongoing_gauge(self):
        if self._g_ongoing is not None:
            self._g_ongoing.set(self._ongoing, tags={
                "deployment": self._deployment, "replica": self._tag})

    @staticmethod
    def _resolve_refs(value):
        """DeploymentRef placeholders (deployment-graph composition)
        become live handles inside the replica."""
        from ray_tpu.serve.api import DeploymentRef, get_deployment_handle

        if isinstance(value, DeploymentRef):
            return get_deployment_handle(value.name)
        if isinstance(value, tuple):
            walked = [_Replica._resolve_refs(v) for v in value]
            # namedtuples construct positionally, not from an iterable
            return (type(value)(*walked) if hasattr(value, "_fields")
                    else tuple(walked))
        if isinstance(value, list):
            return [_Replica._resolve_refs(v) for v in value]
        if isinstance(value, dict):
            return {k: _Replica._resolve_refs(v)
                    for k, v in value.items()}
        return value

    async def handle_request(self, method_name, args, kwargs):
        """ASYNC handler: replicas are asyncio actors (the coroutine here
        puts the hosting worker in async mode), so up to
        max_concurrent_queries requests overlap — async deployment
        methods and ASGI apps at await points, and SYNC handlers in a
        thread executor (the reference replica runs sync user code in a
        thread pool too; a deployment that needs strictly serial
        execution sets max_concurrent_queries=1)."""
        import inspect

        from ray_tpu.serve.multiplex import (MODEL_ID_KWARG,
                                             set_request_model_id)

        model_id = kwargs.pop(MODEL_ID_KWARG, None)
        with self._lock:
            self._ongoing += 1
            self._total += 1
            self._set_ongoing_gauge()
        token = set_request_model_id(model_id)
        # log attribution: lines the handler prints echo/store under the
        # deployment/replica tag instead of the generic actor-method name
        from ray_tpu.runtime import log_plane as _log_plane

        with _log_plane.label_context(
                f"{self._deployment}/{self._tag}"):
            return await self._handle_request_inner(
                method_name, args, kwargs, token)

    async def _handle_request_inner(self, method_name, args, kwargs,
                                    token):
        import inspect

        from ray_tpu.runtime import fault_injection as _fi

        # crash point: request admitted and counted in-flight — the
        # router must fail callers typed-fast and the controller's
        # health probes must replace this replica (chaos replica class)
        _fi.maybe_crash("replica.mid_request")
        try:
            target = (self._instance if method_name == "__call__"
                      else getattr(self._instance, method_name))
            fn = target if (inspect.isfunction(target)
                            or inspect.ismethod(target)) \
                else getattr(target, "__call__", target)
            if inspect.iscoroutinefunction(fn):
                result = await target(*args, **kwargs)
            else:
                # SYNC handler: off the loop (reference: replica runs
                # sync user code in a thread executor) — a blocking
                # model call must not freeze the metrics/other requests
                import asyncio
                import contextvars
                import functools as _ft

                # copy_context: executor threads don't inherit this
                # coroutine's contextvars (the multiplex model id rides
                # on one)
                ctx = contextvars.copy_context()
                result = await asyncio.get_running_loop().run_in_executor(
                    None, _ft.partial(ctx.run, target, *args, **kwargs))
                if inspect.isawaitable(result):
                    # sync wrapper returned a coroutine (e.g. a
                    # @serve.batch-wrapped call): drive it here
                    result = await result
            return result
        finally:
            from ray_tpu.serve.multiplex import _request_model_id

            _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1
                self._set_ongoing_gauge()

    # -- streaming (reference: replica.py handle_request_streaming:323) --

    def start_stream(self, method_name, args, kwargs) -> str:
        """Run a generator method; chunks buffer in a per-stream queue
        drained by next_chunks() calls from the handle. An abandoned
        stream (no consumer drain for 60s against a full queue) tears
        itself down so threads/metrics don't leak."""
        import queue as _q
        import uuid

        from ray_tpu.serve.multiplex import (MODEL_ID_KWARG,
                                             set_request_model_id)

        model_id = kwargs.pop(MODEL_ID_KWARG, None)
        stream_id = uuid.uuid4().hex[:16]
        q: "_q.Queue" = _q.Queue(maxsize=64)
        with self._lock:
            self._streams[stream_id] = q
            self._ongoing += 1
            self._total += 1
            self._set_ongoing_gauge()

        def pump():
            from ray_tpu.runtime import fault_injection as _fi

            token = set_request_model_id(model_id)
            try:
                target = (self._instance if method_name == "__call__"
                          else getattr(self._instance, method_name))
                for chunk in target(*args, **kwargs):
                    # crash point: mid-stream, chunks already delivered —
                    # the consumer's next_chunks call must fail typed-
                    # fast, not hang out a redial window
                    _fi.maybe_crash("replica.mid_decode")
                    q.put(("chunk", chunk), timeout=60.0)
                q.put(("end", None), timeout=60.0)
            except _q.Full:  # consumer gone: abandon the stream
                with self._lock:
                    self._streams.pop(stream_id, None)
            except BaseException as e:  # noqa: BLE001 - ship to consumer
                try:
                    q.put(("error", e), timeout=60.0)
                except _q.Full:
                    with self._lock:
                        self._streams.pop(stream_id, None)
            finally:
                from ray_tpu.serve.multiplex import _request_model_id

                _request_model_id.reset(token)
                with self._lock:
                    self._ongoing -= 1
                    self._set_ongoing_gauge()

        threading.Thread(target=pump, daemon=True).start()
        return stream_id

    async def next_chunks(self, stream_id: str, max_chunks: int = 16,
                          timeout_s: float = 10.0):
        """Up to max_chunks buffered items; final state signals end. A
        generator error is delivered AFTER its preceding chunks: chunks
        already accumulated return normally and the error re-raises on
        the next call. ASYNC wrapper: the blocking queue wait runs in
        the executor in SHORT slices — a long poll parking an executor
        thread for its full timeout would let a handful of idle streams
        starve the shared pool that sync handlers also use."""
        import asyncio
        import functools as _ft
        import time as _time

        loop = asyncio.get_running_loop()
        deadline = _time.monotonic() + timeout_s
        while True:
            slice_s = min(0.25, max(deadline - _time.monotonic(), 0.0))
            result = await loop.run_in_executor(
                None, _ft.partial(self._next_chunks_sync, stream_id,
                                  max_chunks, slice_s))
            if result[0] != "pending" or result[1]:
                return result
            if _time.monotonic() >= deadline:
                return result

    def _next_chunks_sync(self, stream_id: str, max_chunks: int,
                          timeout_s: float):
        import queue as _q

        pending_err = self._stream_errors.pop(stream_id, None)
        if pending_err is not None:
            with self._lock:
                self._streams.pop(stream_id, None)
            raise pending_err
        q = self._streams.get(stream_id)
        if q is None:
            raise KeyError(f"unknown stream {stream_id}")
        out = []
        try:
            kind, payload = q.get(timeout=timeout_s)
        except _q.Empty:
            return ("pending", out)
        while True:
            if kind == "chunk":
                out.append(payload)
            elif kind == "error":
                if out:
                    # deliver data first; error surfaces next call
                    self._stream_errors[stream_id] = payload
                    return ("more", out)
                with self._lock:
                    self._streams.pop(stream_id, None)
                raise payload
            else:  # end
                with self._lock:
                    self._streams.pop(stream_id, None)
                return ("end", out)
            if len(out) >= max_chunks:
                return ("more", out)
            try:
                kind, payload = q.get_nowait()
            except _q.Empty:
                return ("more", out)

    async def reconfigure(self, user_config):
        # off the loop: user reconfigure code may block (model reload)
        import asyncio

        def apply():
            if hasattr(self._instance, "reconfigure"):
                self._instance.reconfigure(user_config)
            return True

        return await asyncio.get_running_loop().run_in_executor(None, apply)

    def metrics(self):
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total}

    def replica_tag(self) -> str:
        return self._tag

    def multiplexed_model_ids(self) -> list:
        from ray_tpu.serve.multiplex import loaded_model_ids

        return loaded_model_ids(self._instance)

    def ping(self):
        return True

    def drain(self):
        """Scale-down prep: retract this replica's prefix digest so the
        affinity router stops steering new prefixes here, and report the
        in-flight count the controller waits on before killing us. The
        route-table version bump already stopped new admissions; any
        straggler from a stale table still gets served."""
        from ray_tpu.runtime import metrics_plane as _mp
        _mp.set_annex(f"serve/prefix_digest/{self._tag}", None)
        with self._lock:
            return self._ongoing


class ServeController:
    """Named actor ('SERVE_CONTROLLER'). Deployment lifecycle + replica
    sets + autoscaling."""

    def __init__(self):
        self._deployments: dict[str, dict] = {}
        self._lock = threading.RLock()
        self._stop = False
        self._version = 0
        # multiplexed model-id sets are POLLED here (throttled, off the
        # request path) and PUSHED to handles inside the routing table,
        # replacing each handle's own per-request 1s-TTL replica sweep
        self._models_polled_at = 0.0
        # proactive failover: periodic replica health probes; each
        # detected death is recorded for MTTR accounting and stamped
        # replaced_at when the reconciler admits the replacement
        self._probed_at = 0.0
        self._probes = 0
        self._crash_events: list[dict] = []
        self._loop = threading.Thread(target=self._control_loop, daemon=True)
        self._loop.start()

    # -- deployment API --------------------------------------------------
    def deployment_meta(self, name: str) -> dict:
        """Static facts the proxies need (e.g. whether the deployment is
        an ASGI ingress, which switches the HTTP proxy to raw-request
        forwarding)."""
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return {}
            return {"asgi": bool(dep["config"].get("asgi"))}

    @staticmethod
    def _same_spec(prev, cls_blob, init_args, init_kwargs,
                   config) -> bool:
        """True when only the replica COUNT differs: that is a scale
        event (graceful drain / spawn), not a code change, and must not
        tear down live replicas. Unpicklable/odd arg objects fail the
        comparison and fall back to the redeploy path (conservative)."""
        try:
            strip = lambda c: {k: v for k, v in c.items()  # noqa: E731
                               if k != "num_replicas"}
            return (prev["cls_blob"] == cls_blob
                    and prev["init_args"] == init_args
                    and prev["init_kwargs"] == init_kwargs
                    and strip(prev["config"]) == strip(config))
        except Exception:  # noqa: BLE001 - uncomparable: full redeploy
            return False

    def deploy(self, name: str, cls_blob: bytes, init_args, init_kwargs,
               config: dict):
        with self._lock:
            prev = self._deployments.get(name)
            self._deployments[name] = {
                "cls_blob": cls_blob,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "config": config,
                "replicas": prev["replicas"] if prev else [],
                "tags": prev["tags"] if prev else [],
                "models": prev["models"] if prev else {},
                "next_idx": prev["next_idx"] if prev else 0,
                "draining": prev.get("draining", []) if prev else [],
                "replaced": prev.get("replaced", 0) if prev else 0,
                "probe_failures": {},
                "autoscale_mode": None,
                "target": (config.get("autoscaling") or {}).get(
                    "min_replicas", config.get("num_replicas", 1))
                if config.get("autoscaling")
                else config.get("num_replicas", 1),
                "last_scale": time.monotonic(),
                "redeploy": prev is not None and not self._same_spec(
                    prev, cls_blob, init_args, init_kwargs, config),
            }
            self._version += 1
        return True

    def delete_deployment(self, name: str):
        with self._lock:
            dep = self._deployments.pop(name, None)
            self._version += 1
        if dep:
            for r in dep["replicas"]:
                _kill_quietly(r)
            for ent in dep.get("draining", ()):
                _kill_quietly(ent["replica"])
        return True

    def get_replicas(self, name: str):
        """(version, [replica handles]) — handles are routable actor refs."""
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return self._version, None
            return self._version, list(dep["replicas"])

    def get_routing_table(self, name: str):
        """(version, [{replica, tag, models}]) — the handle-facing route
        set: actor handles plus stable replica tags (prefix-affinity
        routing keys into these) and each replica's multiplexed
        model-id set (pushed model map — handles no longer sweep
        replicas themselves; the table invalidates on version bumps)."""
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return self._version, None
            models = dep["models"]
            return self._version, [
                {"replica": r, "tag": t, "models": models.get(t, [])}
                for r, t in zip(dep["replicas"], dep["tags"])]

    def version(self) -> int:
        return self._version

    def list_deployments(self):
        with self._lock:
            return {
                name: {"target": dep["target"],
                       "running": len(dep["replicas"]),
                       "autoscale_mode": dep.get("autoscale_mode"),
                       "config": dep["config"]}
                for name, dep in self._deployments.items()
            }

    def shutdown(self):
        self._stop = True
        with self._lock:
            deps = list(self._deployments.values())
            self._deployments.clear()
        for dep in deps:
            for r in dep["replicas"]:
                _kill_quietly(r)
            for ent in dep.get("draining", ()):
                _kill_quietly(ent["replica"])
        return True

    def failover_stats(self):
        """Replica-failover accounting for the chaos soak's MTTR: one
        event per probed-out replica with detection and replacement
        timestamps, plus per-deployment replacement totals."""
        with self._lock:
            return {
                "events": [dict(e) for e in self._crash_events],
                "replaced": {n: d.get("replaced", 0)
                             for n, d in self._deployments.items()},
                "draining": {n: len(d.get("draining", ()))
                             for n, d in self._deployments.items()},
                "probes": self._probes,
            }

    # -- reconciliation --------------------------------------------------
    def _control_loop(self):
        while not self._stop:
            try:
                self._reconcile_once()
                self._drain_once()
                self._health_probe_once()
                self._poll_models_once()
                self._autoscale_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                pass
            time.sleep(0.1)

    def _reconcile_once(self):
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            if dep.get("redeploy"):
                # config/code changed: replace replica set (reference:
                # rolling update; v1 does stop-then-start)
                old = dep["replicas"]
                dep["replicas"] = []
                dep["tags"] = []
                dep["models"] = {}
                dep["redeploy"] = False
                for r in old:
                    _kill_quietly(r)
                with self._lock:
                    self._version += 1
            target = dep["target"]
            replicas = dep["replicas"]
            while len(replicas) < target:
                replica_cls = ray_tpu.remote(_Replica)
                res = dep["config"].get("resources_per_replica") or {}
                opts = {"max_concurrency":
                        dep["config"].get("max_concurrent_queries", 8)}
                if res.get("CPU"):
                    opts["num_cpus"] = res["CPU"]
                if res.get("TPU"):
                    opts["num_tpus"] = res["TPU"]
                tag = f"{name}#r{dep['next_idx']}"
                dep["next_idx"] += 1
                handle = replica_cls.options(**opts).remote(
                    dep["cls_blob"], dep["init_args"], dep["init_kwargs"],
                    dep["config"].get("user_config") or {},
                    deployment=name, replica_tag=tag)
                replicas.append(handle)
                dep["tags"].append(tag)
                with self._lock:
                    self._version += 1
                    # a spawn while crash events are pending IS the
                    # replacement: stamp the oldest unreplaced one
                    for ev in self._crash_events:
                        if (ev["deployment"] == name
                                and ev["replaced_at"] is None):
                            ev["replaced_at"] = time.time()
                            break
            while len(replicas) > target:
                # graceful scale-down: unpublish the route first (the
                # version bump stops new admissions), let in-flight
                # requests finish; _drain_once kills when ongoing hits
                # zero or the drain deadline passes
                victim = replicas.pop()
                tag = dep["tags"].pop() if dep["tags"] else None
                dep["models"].pop(tag, None)
                dep.setdefault("draining", []).append(
                    {"replica": victim, "tag": tag,
                     "since": time.monotonic(), "drained": False})
                with self._lock:
                    self._version += 1

    def _drain_once(self):
        from ray_tpu.utils import exceptions
        from ray_tpu.utils.config import get_config
        cfg = get_config()
        with self._lock:
            items = list(self._deployments.items())
        for _name, dep in items:
            keep = []
            for ent in dep.get("draining", ()):
                r = ent["replica"]
                try:
                    if not ent["drained"]:
                        # one-shot: retract the prefix digest, get the
                        # in-flight count to wait on
                        ongoing = ray_tpu.get(r.drain.remote(), timeout=2)
                        ent["drained"] = True
                    else:
                        ongoing = ray_tpu.get(
                            r.metrics.remote(), timeout=2)["ongoing"]
                except exceptions.ActorError:
                    ongoing = 0    # already dead: reap the handle
                except Exception:  # noqa: BLE001 - busy/slow, NOT dead
                    # a replica mid-request can miss the poll timeout;
                    # only the drain deadline may condemn it
                    ongoing = 1
                deadline = ent["since"] + cfg.serve_drain_timeout_s
                if ongoing <= 0 or time.monotonic() > deadline:
                    _kill_quietly(r)
                else:
                    keep.append(ent)
            dep["draining"] = keep

    def _health_probe_once(self):
        """Proactively ping every replica; replace ones that died
        instead of waiting for a request to trip over the corpse. A
        typed actor-death error is immediate; bare timeouts must repeat
        ``serve_health_probe_failures`` times (a busy replica is slow,
        not dead)."""
        from ray_tpu.utils import exceptions as exc
        from ray_tpu.utils.config import get_config
        cfg = get_config()
        if not cfg.serve_health_probing_enabled:
            return
        now = time.monotonic()
        if now - self._probed_at < cfg.serve_health_probe_period_s:
            return
        self._probed_at = now
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            fails = dep.setdefault("probe_failures", {})
            for r, tag in list(zip(dep["replicas"], dep["tags"])):
                dead = False
                self._probes += 1
                try:
                    ray_tpu.get(r.ping.remote(),
                                timeout=cfg.serve_health_probe_timeout_s)
                    fails.pop(tag, None)
                except exc.ActorError:
                    dead = True
                except Exception:  # noqa: BLE001 - timeout/transport
                    fails[tag] = fails.get(tag, 0) + 1
                    dead = fails[tag] >= cfg.serve_health_probe_failures
                if dead:
                    self._bury_replica(name, dep, r, tag)

    def _bury_replica(self, name: str, dep: dict, replica, tag):
        """Drop a crashed replica from the route set NOW (the version
        bump makes stale handles re-pull and fail in-flight calls fast)
        and leave len(replicas) < target for the reconciler to refill."""
        with self._lock:
            try:
                i = dep["tags"].index(tag)
            except ValueError:
                return    # already buried by a racing path
            dep["replicas"].pop(i)
            dep["tags"].pop(i)
            dep["models"].pop(tag, None)
            dep.setdefault("probe_failures", {}).pop(tag, None)
            dep["replaced"] = dep.get("replaced", 0) + 1
            self._version += 1
            self._crash_events.append({
                "deployment": name, "tag": tag,
                "detected_at": time.time(), "replaced_at": None})
        _kill_quietly(replica)

    def _poll_models_once(self, interval_s: float = 0.25):
        """Refresh each replica's multiplexed model-id set (throttled).
        Changes bump the routing-table version, so handles re-pull the
        pushed model map instead of sweeping replicas per request."""
        now = time.monotonic()
        if now - self._models_polled_at < interval_s:
            return
        self._models_polled_at = now
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            pairs = list(zip(dep["replicas"], dep["tags"]))
            models = {}
            for r, t in pairs:
                try:
                    models[t] = sorted(ray_tpu.get(
                        r.multiplexed_model_ids.remote(), timeout=2))
                except Exception:  # noqa: BLE001 - dead replica: keep last
                    models[t] = dep["models"].get(t, [])
            if models != dep["models"]:
                dep["models"] = models
                with self._lock:
                    self._version += 1

    def _autoscale_once(self):
        now = time.monotonic()
        with self._lock:
            items = list(self._deployments.items())
        for name, dep in items:
            auto = dep["config"].get("autoscaling")
            if not auto or not dep["replicas"]:
                continue
            n = len(dep["replicas"])
            signals = None
            if auto.get("policy", "metrics") == "metrics":
                signals = self._pushed_signals(name, auto)
            queue_p50 = kv_occ = None
            if signals is not None:
                dep["autoscale_mode"] = "metrics"
                per_replica = signals["ongoing"] / n
                queue_p50 = signals.get("queue_wait_p50")
                kv_occ = signals.get("kv_occupancy")
            else:
                # pushed windows missing or stale (metrics plane
                # partitioned, or nothing flowing yet): degrade to the
                # original polled per-replica loop — scaling must not
                # stop because observability did
                dep["autoscale_mode"] = "polled"
                try:
                    metrics = ray_tpu.get(
                        [r.metrics.remote() for r in dep["replicas"]],
                        timeout=5)
                except Exception:  # noqa: BLE001
                    continue
                per_replica = sum(m["ongoing"] for m in metrics) / n
            target_per = auto.get("target_ongoing_requests", 2.0)
            hot_queue = (queue_p50 is not None and queue_p50
                         > auto.get("upscale_queue_wait_s", 0.25))
            hot_kv = (kv_occ is not None and kv_occ
                      > auto.get("kv_upscale_occupancy", 0.9))
            want_up = per_replica > target_per or hot_queue or hot_kv
            want_down = (per_replica < target_per / 2
                         and not hot_queue and not hot_kv)
            if (want_up
                    and dep["target"] < auto.get("max_replicas", 4)
                    and now - dep["last_scale"] > auto.get(
                        "upscale_delay_s", 0.5)):
                dep["target"] += 1
                dep["last_scale"] = now
            elif (want_down
                    and dep["target"] > auto.get("min_replicas", 1)
                    and now - dep["last_scale"] > auto.get(
                        "downscale_delay_s", 2.0)):
                dep["target"] -= 1
                dep["last_scale"] = now

    def _pushed_signals(self, name: str, auto: dict) -> dict | None:
        """Windowed autoscaling signals from the cluster metrics plane,
        or None when the plane has nothing fresh for this deployment —
        the caller then degrades to the polled loop. The GCS keeps its
        own windows rolling during a metrics-plane partition (its self
        loop ingests locally), so partitioned replicas' series age out
        of the query horizon within ~one window and this returns None
        without any explicit partition detector."""
        horizon = auto.get("metrics_window_s", 3.0)
        try:
            from ray_tpu.util.state import cluster_metrics
            res = cluster_metrics("ray_tpu_serve_ongoing",
                                  tags={"deployment": name},
                                  last_s=horizon)
            if res.get("kind") is None or not res.get("groups"):
                return None
            out = {"ongoing": float(sum(
                g["value"] for g in res["groups"]))}
            qres = cluster_metrics("ray_tpu_serve_stage_s",
                                   tags={"stage": "queue_wait",
                                         "deployment": name},
                                   last_s=horizon)
            from ray_tpu.runtime.metrics_plane import summarize_histogram
            digest = summarize_histogram(qres, quantiles=(0.5,))
            if digest.get("count"):
                out["queue_wait_p50"] = digest["p50"]
            kres = cluster_metrics("ray_tpu_serve_kv_pages",
                                   tags={"deployment": name},
                                   group_by=("state",),
                                   last_s=horizon)
            kv = {g["tags"].get("state"): g["value"]
                  for g in kres.get("groups", ())}
            if kv.get("total"):
                out["kv_occupancy"] = max(
                    0.0, 1.0 - kv.get("free", 0.0) / kv["total"])
            return out
        except Exception:  # noqa: BLE001 - plane unreachable: degrade
            return None


def _kill_quietly(handle):
    try:
        ray_tpu.kill(handle)
    except Exception:  # noqa: BLE001
        pass
