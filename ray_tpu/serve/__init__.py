"""ray_tpu.serve: model serving (reference: Ray Serve, SURVEY P15)."""

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("serve")


from ray_tpu.serve.api import (
    batch,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http_proxy,
    status,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.ingress import grpc_call, ingress, start_grpc_proxy
from ray_tpu.serve.schema import apply_config, apply_config_file

__all__ = [
    "AutoscalingConfig",
    "DeploymentConfig",
    "DeploymentHandle",
    "apply_config",
    "apply_config_file",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "grpc_call",
    "ingress",
    "multiplexed",
    "run",
    "shutdown",
    "start_grpc_proxy",
    "start_http_proxy",
    "status",
]
