"""DeploymentHandle + router: replica selection per request.

Reference analog: ``serve/handle.py`` (``DeploymentHandle:804``) and
``serve/_private/router.py`` — ``PowerOfTwoChoicesReplicaScheduler:290``:
pick two random replicas, route to the one with fewer in-flight requests.
In-flight counts are tracked client-side (each handle knows what it sent
and what completed), so the hot path makes zero control-plane calls; the
replica set refreshes when the controller version changes (long-poll
analog: cheap version check with TTL)."""

from __future__ import annotations

import random
import threading
import time

import ray_tpu


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller, method_name="__call__"):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._replicas: list = []
        self._version = -1
        self._checked_at = 0.0
        self._lock = threading.Lock()
        self._inflight: dict = {}   # replica -> count

    def options(self, *, method_name: str) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name)
        h._replicas, h._version = self._replicas, self._version
        h._inflight = self._inflight
        return h

    # -- replica set refresh (long-poll analog) -------------------------
    def _refresh(self, ttl: float = 0.2):
        now = time.monotonic()
        with self._lock:
            if self._replicas and now - self._checked_at < ttl:
                return
        version = ray_tpu.get(self._controller.version.remote())
        with self._lock:
            if version == self._version and self._replicas:
                self._checked_at = now
                return
        version, replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self.deployment_name))
        if replicas is None:
            raise KeyError(
                f"deployment {self.deployment_name!r} does not exist")
        with self._lock:
            self._replicas = replicas
            self._version = version
            self._checked_at = now
            self._inflight = {r: self._inflight.get(r, []) for r in replicas}

    def _prune(self, replica):
        """Drop completed refs from a replica's outstanding list (non-
        blocking); returns the remaining in-flight count."""
        refs = self._inflight.get(replica, [])
        if refs:
            ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                            timeout=0)
            self._inflight[replica] = not_ready
            return len(not_ready)
        return 0

    def _pick(self):
        """Power-of-two-choices on client-side outstanding-request counts
        (pruned at pick time — no background bookkeeping threads)."""
        with self._lock:
            replicas = self._replicas
            if not replicas:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
            if len(replicas) == 1:
                return replicas[0]
            a, b = random.sample(replicas, 2)
            return a if self._prune(a) <= self._prune(b) else b

    # -- request path ----------------------------------------------------
    def remote(self, *args, **kwargs):
        """Async call → ObjectRef (resolve with ray_tpu.get)."""
        self._refresh()
        last = None
        for attempt in range(5):
            try:
                replica = self._pick()  # raises during redeploy gap
                ref = replica.handle_request.remote(self._method, args,
                                                    kwargs)
                with self._lock:
                    self._inflight.setdefault(replica, []).append(ref)
                return ref
            except Exception as e:  # noqa: BLE001 - dead replica / empty set
                last = e
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * attempt)
                self._refresh(ttl=0)
        raise RuntimeError(
            f"could not route request to {self.deployment_name!r}: {last!r}")

    def call(self, *args, **kwargs):
        """Sync convenience: remote + get. A replica torn down mid-request
        (redeploy/downscale) surfaces at get(); retry against the
        refreshed replica set (reference: router resend on replica death)."""
        from ray_tpu.utils.exceptions import ActorError

        last = None
        for attempt in range(3):
            try:
                return ray_tpu.get(self.remote(*args, **kwargs))
            except ActorError as e:
                last = e
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * (attempt + 1))
        raise last
