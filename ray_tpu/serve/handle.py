"""DeploymentHandle + router: replica selection per request.

Reference analog: ``serve/handle.py`` (``DeploymentHandle:804``) and
``serve/_private/router.py`` — ``PowerOfTwoChoicesReplicaScheduler:290``:
pick two random replicas, route to the one with fewer in-flight requests.
In-flight counts are tracked client-side (each handle knows what it sent
and what completed), so the hot path makes zero control-plane calls; the
replica set refreshes when the controller version changes (long-poll
analog: cheap version check with TTL)."""

from __future__ import annotations

import random
import threading
import time

import ray_tpu
from ray_tpu.util import tracing as _tracing
from ray_tpu.utils.exceptions import ActorError, ReplicaDiedError


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name="__call__", multiplexed_model_id=None):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._replicas: list = []
        self._version = -1
        self._checked_at = 0.0
        self._lock = threading.Lock()
        self._inflight: dict = {}    # replica -> outstanding refs
        self._tags: dict = {}        # replica -> controller replica tag
        self._model_map: dict = {}   # model id -> [replicas] (pushed)
        self._router = None          # lazy PrefixRouter
        self._router_at = 0.0

    def options(self, *, method_name: str | None = None,
                multiplexed_model_id: str | None = None
                ) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method,
                             multiplexed_model_id or self._model_id)
        h._replicas, h._version = self._replicas, self._version
        h._inflight = self._inflight
        h._tags, h._model_map = self._tags, self._model_map
        h._router = self._router
        return h

    # -- replica set refresh (long-poll analog) -------------------------
    def _refresh(self, ttl: float = 0.2):
        now = time.monotonic()
        with self._lock:
            if self._replicas and now - self._checked_at < ttl:
                return
        version = ray_tpu.get(self._controller.version.remote())
        with self._lock:
            if version == self._version and self._replicas:
                self._checked_at = now
                return
        version, table = ray_tpu.get(
            self._controller.get_routing_table.remote(
                self.deployment_name))
        if table is None:
            raise KeyError(
                f"deployment {self.deployment_name!r} does not exist")
        replicas = [e["replica"] for e in table]
        model_map: dict = {}
        for e in table:
            for mid in e["models"]:
                model_map.setdefault(mid, []).append(e["replica"])
        with self._lock:
            old_tags = set(self._tags.values())
            self._replicas = replicas
            self._tags = {e["replica"]: e["tag"] for e in table}
            self._model_map = model_map
            self._version = version
            self._checked_at = now
            self._inflight = {r: self._inflight.get(r, []) for r in replicas}
            gone = old_tags - set(self._tags.values())
            router = self._router
        # tags the controller dropped (crash/drain) lose their prefix-
        # digest routing entries immediately — the annex TTL would keep
        # steering warm prefixes at a corpse for seconds otherwise
        if router is not None:
            for tag in gone:
                router.forget(tag)

    def _evict(self, replica):
        """Drop a failed replica from every routing structure NOW: the
        controller's reconciler takes a beat to notice the death, and
        until it bumps the version this handle's maps would happily
        re-pick the corpse (the stale-map window). The next refresh
        re-adds the replica if it was actually alive."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r != replica]
            tag = self._tags.pop(replica, None)
            self._inflight.pop(replica, None)
            for mid, lst in list(self._model_map.items()):
                if replica in lst:
                    self._model_map[mid] = [
                        r for r in lst if r != replica]
            self._version = -1
        if tag is not None and self._router is not None:
            self._router.forget(tag)

    def _prune(self, replica):
        """Drop completed refs from a replica's outstanding list (non-
        blocking); returns the remaining in-flight count."""
        refs = self._inflight.get(replica, [])
        if refs:
            ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                            timeout=0)
            self._inflight[replica] = not_ready
            return len(not_ready)
        return 0

    def _pick(self, prefix_tokens=None):
        """Power-of-two-choices on client-side outstanding-request counts
        (pruned at pick time — no background bookkeeping threads). With a
        multiplexed model id, cache-affinity comes first: prefer replicas
        that already hold the model (reference:
        multiplexed_replica_info routing in the replica scheduler). With
        ``prefix_tokens``, prefix-cache affinity comes first: route to
        the replica whose published digest already holds the longest
        leading page run (serve/prefix_router.py), falling back to p2c
        when no digest matches."""
        if prefix_tokens is not None:
            replica = self._affinity_pick(prefix_tokens)
            if replica is not None:
                return replica
        if self._model_id is not None:
            warm = self._replicas_with_model(self._model_id)
            if warm:
                with self._lock:
                    if len(warm) == 1:
                        return warm[0]
                    a, b = random.sample(warm, 2)
                    return a if self._prune(a) <= self._prune(b) else b
        with self._lock:
            replicas = self._replicas
            if not replicas:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
            if len(replicas) == 1:
                return replicas[0]
            a, b = random.sample(replicas, 2)
            return a if self._prune(a) <= self._prune(b) else b

    def _replicas_with_model(self, model_id: str) -> list:
        """Replicas that currently hold model_id loaded — a LOCAL
        lookup into the controller-pushed model map (refreshed with the
        routing table on version bumps; the controller polls replicas
        off the request path, so the per-request N-round-trip sweep the
        old TTL cache amortized is gone entirely)."""
        with self._lock:
            return list(self._model_map.get(model_id, []))

    def _affinity_pick(self, tokens):
        """Prefix-affinity choice, or None for the p2c fallback. Digest
        pulls are throttled to the publish interval and best-effort: a
        partitioned metrics plane just means stale digests expire and
        every pick falls back."""
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        if not cfg.serve_prefix_routing_enabled:
            return None
        if self._router is None:
            from ray_tpu.serve.prefix_router import PrefixRouter

            self._router = PrefixRouter()
        now = time.monotonic()
        if now - self._router_at >= cfg.serve_digest_publish_interval_s:
            self._router_at = now
            try:
                from ray_tpu.serve.prefix_router import DIGEST_PREFIX
                from ray_tpu.util.state import cluster_metric_annexes

                self._router.ingest(cluster_metric_annexes(
                    DIGEST_PREFIX, max_age_s=cfg.serve_digest_ttl_s))
            except Exception:  # noqa: BLE001 - best-effort: TTL expires stale
                pass
        with self._lock:
            by_tag = {t: r for r, t in self._tags.items()}
            candidates = {t: len(self._inflight.get(r, ()))
                          for t, r in by_tag.items()}
        tag = self._router.pick(tokens, candidates)
        return by_tag.get(tag) if tag is not None else None

    # -- request path ----------------------------------------------------
    def remote(self, *args, **kwargs):
        """Async call → ObjectRef (resolve with ray_tpu.get). The
        reserved ``_prefix_tokens`` kwarg (the request's prompt token
        list) opts the call into prefix-affinity routing; it is stripped
        before the replica sees the arguments."""
        prefix_tokens = kwargs.pop("_prefix_tokens", None)
        if self._model_id is not None:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}
        self._refresh()
        last = None
        # trace root of the serve path: the actor call below captures
        # this ambient span into its task spec, so router, replica run
        # span, and engine stage spans all land in ONE trace
        with _tracing.span(f"serve.request:{self.deployment_name}",
                           kind="serve"):
            for attempt in range(5):
                replica = None
                try:
                    with _tracing.span("serve.route", kind="serve"):
                        # raises in redeploy gap
                        replica = self._pick(prefix_tokens)
                    ref = replica.handle_request.remote(self._method, args,
                                                        kwargs)
                    with self._lock:
                        self._inflight.setdefault(replica, []).append(ref)
                    return ref
                except Exception as e:  # noqa: BLE001 - dead/empty set
                    last = e
                    if replica is not None:
                        self._evict(replica)
                    with self._lock:
                        self._version = -1
                    time.sleep(0.05 * attempt)
                    self._refresh(ttl=0)
        raise ReplicaDiedError(
            deployment=self.deployment_name,
            reason=f"could not route request after 5 attempts: {last!r}")

    def stream(self, *args, **kwargs):
        """Call a GENERATOR method and iterate its chunks as they are
        produced (reference: replica handle_request_streaming:323 +
        streaming DeploymentResponse). Chunks batch over the wire
        (next_chunks) so per-chunk overhead amortizes. Stream START
        retries against a refreshed replica set like remote(); once
        streaming, a replica death surfaces to the consumer."""
        prefix_tokens = kwargs.pop("_prefix_tokens", None)
        if self._model_id is not None:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}
        self._refresh()
        last = None
        for attempt in range(5):
            replica = None
            try:
                with _tracing.span(
                        f"serve.request:{self.deployment_name}",
                        kind="serve"):
                    with _tracing.span("serve.route", kind="serve"):
                        replica = self._pick(prefix_tokens)
                    stream_id = ray_tpu.get(
                        replica.start_stream.remote(self._method, args,
                                                    kwargs))
                break
            except Exception as e:  # noqa: BLE001 - stale/dead replica
                last = e
                if replica is not None:
                    self._evict(replica)
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * attempt)
                self._refresh(ttl=0)
        else:
            raise ReplicaDiedError(
                deployment=self.deployment_name,
                reason=f"could not start stream after 5 attempts: {last!r}")

        with self._lock:
            tag = self._tags.get(replica)

        def gen():
            while True:
                try:
                    state, chunks = ray_tpu.get(
                        replica.next_chunks.remote(stream_id))
                except ActorError as e:
                    # replica died mid-stream: fail the consumer fast
                    # with a typed error (a retry cannot resume a half-
                    # emitted stream) and stop routing at the corpse
                    self._evict(replica)
                    raise ReplicaDiedError(
                        tag, self.deployment_name,
                        reason=f"died mid-stream: {e!r}") from e
                yield from chunks
                if state == "end":
                    return

        return gen()

    def call(self, *args, **kwargs):
        """Sync convenience: remote + get. A replica torn down mid-request
        (redeploy/downscale) surfaces at get(); retry against the
        refreshed replica set (reference: router resend on replica death)."""
        last = None
        tag = None
        for attempt in range(3):
            ref = None
            try:
                ref = self.remote(*args, **kwargs)
                return ray_tpu.get(ref)
            except ActorError as e:
                last = e
                owner = self._owner_of(ref) if ref is not None else None
                if owner is not None:
                    with self._lock:
                        tag = self._tags.get(owner, tag)
                    self._evict(owner)
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * (attempt + 1))
        if isinstance(last, ReplicaDiedError):
            raise last
        raise ReplicaDiedError(
            tag, self.deployment_name,
            reason=f"call failed after 3 attempts: {last!r}") from last

    def _owner_of(self, ref):
        with self._lock:
            for r, refs in self._inflight.items():
                if ref in refs:
                    return r
        return None
