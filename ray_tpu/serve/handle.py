"""DeploymentHandle + router: replica selection per request.

Reference analog: ``serve/handle.py`` (``DeploymentHandle:804``) and
``serve/_private/router.py`` — ``PowerOfTwoChoicesReplicaScheduler:290``:
pick two random replicas, route to the one with fewer in-flight requests.
In-flight counts are tracked client-side (each handle knows what it sent
and what completed), so the hot path makes zero control-plane calls; the
replica set refreshes when the controller version changes (long-poll
analog: cheap version check with TTL)."""

from __future__ import annotations

import random
import threading
import time

import ray_tpu


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name="__call__", multiplexed_model_id=None):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._replicas: list = []
        self._version = -1
        self._checked_at = 0.0
        self._lock = threading.Lock()
        self._inflight: dict = {}   # replica -> count

    def options(self, *, method_name: str | None = None,
                multiplexed_model_id: str | None = None
                ) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method,
                             multiplexed_model_id or self._model_id)
        h._replicas, h._version = self._replicas, self._version
        h._inflight = self._inflight
        return h

    # -- replica set refresh (long-poll analog) -------------------------
    def _refresh(self, ttl: float = 0.2):
        now = time.monotonic()
        with self._lock:
            if self._replicas and now - self._checked_at < ttl:
                return
        version = ray_tpu.get(self._controller.version.remote())
        with self._lock:
            if version == self._version and self._replicas:
                self._checked_at = now
                return
        version, replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self.deployment_name))
        if replicas is None:
            raise KeyError(
                f"deployment {self.deployment_name!r} does not exist")
        with self._lock:
            self._replicas = replicas
            self._version = version
            self._checked_at = now
            self._inflight = {r: self._inflight.get(r, []) for r in replicas}

    def _prune(self, replica):
        """Drop completed refs from a replica's outstanding list (non-
        blocking); returns the remaining in-flight count."""
        refs = self._inflight.get(replica, [])
        if refs:
            ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                            timeout=0)
            self._inflight[replica] = not_ready
            return len(not_ready)
        return 0

    def _pick(self):
        """Power-of-two-choices on client-side outstanding-request counts
        (pruned at pick time — no background bookkeeping threads). With a
        multiplexed model id, cache-affinity comes first: prefer replicas
        that already hold the model (reference:
        multiplexed_replica_info routing in the replica scheduler)."""
        if self._model_id is not None:
            warm = self._replicas_with_model(self._model_id)
            if warm:
                with self._lock:
                    if len(warm) == 1:
                        return warm[0]
                    a, b = random.sample(warm, 2)
                    return a if self._prune(a) <= self._prune(b) else b
        with self._lock:
            replicas = self._replicas
            if not replicas:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
            if len(replicas) == 1:
                return replicas[0]
            a, b = random.sample(replicas, 2)
            return a if self._prune(a) <= self._prune(b) else b

    def _replicas_with_model(self, model_id: str) -> list:
        """Replicas that currently hold model_id loaded. Cached with a
        short TTL: polling every replica per request would put N
        round-trips on the hot path (reference pushes model-id sets to
        the router; a TTL cache is the pull-model equivalent)."""
        now = time.monotonic()
        with self._lock:
            cache = getattr(self, "_model_map", None)
            if cache is not None and now - self._model_map_at < 1.0:
                return cache.get(model_id, [])
            replicas = list(self._replicas)
        model_map: dict = {}
        for r in replicas:
            try:
                for mid in ray_tpu.get(r.multiplexed_model_ids.remote(),
                                       timeout=2):
                    model_map.setdefault(mid, []).append(r)
            except Exception:  # noqa: BLE001 - dead replica: skip
                continue
        with self._lock:
            self._model_map = model_map
            self._model_map_at = now
        return model_map.get(model_id, [])

    # -- request path ----------------------------------------------------
    def remote(self, *args, **kwargs):
        """Async call → ObjectRef (resolve with ray_tpu.get)."""
        if self._model_id is not None:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}
        self._refresh()
        last = None
        for attempt in range(5):
            try:
                replica = self._pick()  # raises during redeploy gap
                ref = replica.handle_request.remote(self._method, args,
                                                    kwargs)
                with self._lock:
                    self._inflight.setdefault(replica, []).append(ref)
                return ref
            except Exception as e:  # noqa: BLE001 - dead replica / empty set
                last = e
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * attempt)
                self._refresh(ttl=0)
        raise RuntimeError(
            f"could not route request to {self.deployment_name!r}: {last!r}")

    def stream(self, *args, **kwargs):
        """Call a GENERATOR method and iterate its chunks as they are
        produced (reference: replica handle_request_streaming:323 +
        streaming DeploymentResponse). Chunks batch over the wire
        (next_chunks) so per-chunk overhead amortizes. Stream START
        retries against a refreshed replica set like remote(); once
        streaming, a replica death surfaces to the consumer."""
        if self._model_id is not None:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}
        self._refresh()
        last = None
        for attempt in range(5):
            try:
                replica = self._pick()
                stream_id = ray_tpu.get(
                    replica.start_stream.remote(self._method, args,
                                                kwargs))
                break
            except Exception as e:  # noqa: BLE001 - stale/dead replica
                last = e
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * attempt)
                self._refresh(ttl=0)
        else:
            raise RuntimeError(
                f"could not start stream on {self.deployment_name!r}: "
                f"{last!r}")

        def gen():
            while True:
                state, chunks = ray_tpu.get(
                    replica.next_chunks.remote(stream_id))
                yield from chunks
                if state == "end":
                    return

        return gen()

    def call(self, *args, **kwargs):
        """Sync convenience: remote + get. A replica torn down mid-request
        (redeploy/downscale) surfaces at get(); retry against the
        refreshed replica set (reference: router resend on replica death)."""
        from ray_tpu.utils.exceptions import ActorError

        last = None
        for attempt in range(3):
            try:
                return ray_tpu.get(self.remote(*args, **kwargs))
            except ActorError as e:
                last = e
                with self._lock:
                    self._version = -1
                time.sleep(0.05 * (attempt + 1))
        raise last
