"""Model multiplexing: many models per deployment, LRU per replica.

Reference analog: ``serve/multiplex.py`` (``_ModelMultiplexWrapper:23``)
and ``serve/api.py:575`` (``@serve.multiplexed``). A deployment method
decorated with ``@serve.multiplexed(max_num_models_per_replica=N)``
loads a model by id; the wrapper keeps an LRU of loaded models per
replica (evicting with ``__del__``-style drop), and the router prefers
replicas that already hold the requested model (cache-affinity routing)
over cold ones.

Request flow: ``handle.options(multiplexed_model_id="m1").remote(x)`` —
the id rides the request as a reserved kwarg, the replica sets the
request context, and user code calls
``serve.get_multiplexed_model_id()`` inside the loader/handler.
"""

from __future__ import annotations

import contextvars
import functools
import threading
from collections import OrderedDict

MODEL_ID_KWARG = "__serve_multiplexed_model_id__"

_request_model_id: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("serve_multiplexed_model_id", default=None)


def get_multiplexed_model_id() -> str | None:
    """Inside a request: the model id this request was routed with."""
    return _request_model_id.get()


def set_request_model_id(model_id: str | None):
    return _request_model_id.set(model_id)


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for the model-loader method of a deployment class:

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str): ...

    Calls are LRU-cached per model id; eviction drops the oldest model.
    """

    def wrap(fn):
        attr = f"__serve_multiplex_state_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, model_id: str | None = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            if model_id is None:
                raise ValueError(
                    "no model id: pass one or route the request with "
                    "handle.options(multiplexed_model_id=...)")
            state = self.__dict__.setdefault(
                attr, {"models": OrderedDict(), "lock": threading.Lock()})
            with state["lock"]:
                if model_id in state["models"]:
                    state["models"].move_to_end(model_id)
                    return state["models"][model_id]
            model = fn(self, model_id)  # load OUTSIDE the lock (slow)
            with state["lock"]:
                state["models"][model_id] = model
                state["models"].move_to_end(model_id)
                while len(state["models"]) > max_num_models_per_replica:
                    state["models"].popitem(last=False)
            return model

        wrapper.__serve_multiplexed__ = True
        return wrapper

    return wrap if _fn is None else wrap(_fn)


def loaded_model_ids(instance) -> list[str]:
    """All model ids currently cached on a replica instance (across its
    multiplexed methods)."""
    out: list[str] = []
    for key, state in instance.__dict__.items():
        if key.startswith("__serve_multiplex_state_"):
            out.extend(state["models"].keys())
    return out
