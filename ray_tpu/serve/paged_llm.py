"""Paged-KV continuous-batching LLM engine.

Reference: ABSENT from the reference repo (it serves models via user
code in replicas — SURVEY.md P15). This engine wires the vLLM-style
paged KV allocator (``ray_tpu/ops/paged_attention.py``) into the
continuous-batching loop of ``serve/llm.py``:

- The KV cache is a POOL of fixed-size pages [L, P, page, nkv, hd];
  each slot owns a page list. HBM scales with TOKENS IN FLIGHT
  (reserved per request = prompt + max_new_tokens), not with
  ``max_batch * max_len`` — a 256-token chat on a 2048-token engine
  stops reserving 8x its need.
- Decode attends over a BUCKETED page window: the gather width is the
  power-of-two page count covering the longest live sequence, so short
  workloads read a fraction of the dense cache's KV bytes per step
  (the dominant decode-step HBM traffic at small models).
- Allocation is reserve-on-admit (pages for prompt + budget + one
  chained-overshoot page, released at retirement): admission applies
  backpressure when the pool is exhausted, and a mid-flight sequence
  can never fail an allocation — the deadlock-free policy (optimistic
  allocation + preemption is a future extension).
- ``kv_dtype="int8"`` stores pages quantized (per-token-per-head
  symmetric scales in a parallel scale pool): half the KV HBM, so the
  same pool holds 2x the tokens in flight. Dequantization happens on
  gather — a VPU cost per decode step — so it's a CAPACITY trade, the
  right default only when KV footprint is the binding constraint
  (long contexts / many concurrent slots); at small windows where
  decode is weight-read-bound it measures ~35% slower (v5e, 0.5B).

Engine mechanics (queues, continuous batching, chunked + pipelined
decode, metrics) are inherited from ``LLMEngine``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.models.decoding import (_cached_attention,
                                     select_tokens)
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.paged_attention import (PageAllocator, PrefixCache,
                                         dequantize_kv, page_hashes,
                                         quantize_kv)
from ray_tpu.ops.rope import apply_rope, rope_sin_cos
from ray_tpu.serve.llm import LLMEngine, _bucket


def _write_gather_kv(kp, vp, ks, vs, k_new, v_new, pidx, ip, table_c,
                     quantized):
    """THE write-then-gather KV protocol, shared by decode and prefill
    (shape-generic: decode writes one token per slot with [B] indices,
    prefill a padded suffix with [n, T] indices). Writes k/v (+ scales
    in int8 mode) at (pidx, ip) with out-of-bounds indices dropping,
    then gathers the table_c page window, dequantizing if quantized."""
    if quantized:
        kq, ksc = quantize_kv(k_new)
        vq, vsc = quantize_kv(v_new)
        kp = kp.at[pidx, ip].set(kq, mode="drop")
        vp = vp.at[pidx, ip].set(vq, mode="drop")
        ks = ks.at[pidx, ip].set(ksc, mode="drop")
        vs = vs.at[pidx, ip].set(vsc, mode="drop")
        kg = dequantize_kv(kp[table_c], ks[table_c])
        vg = dequantize_kv(vp[table_c], vs[table_c])
    else:
        kp = kp.at[pidx, ip].set(k_new.astype(kp.dtype), mode="drop")
        vp = vp.at[pidx, ip].set(v_new.astype(vp.dtype), mode="drop")
        kg, vg = kp[table_c], vp[table_c]
    return kp, vp, ks, vs, kg, vg


class PagedLLMEngine(LLMEngine):
    """LLMEngine with a paged KV cache (see module docstring).

    With ``prefix_cache=True`` (default), full prompt pages are also a
    content-addressed PREFIX CACHE (vLLM-style automatic prefix caching,
    chained page hashes — reference repo has no serving engine at all):
    a new request whose prompt starts with an already-cached page chain
    reuses those pages read-only and prefills only the suffix, cutting
    both TTFT and prefill compute for shared-system-prompt workloads.
    Unreferenced cached pages stay resident and are evicted LRU only
    when admission needs their space."""

    def __init__(self, cfg, params, *, max_batch: int = 8,
                 max_len: int = 2048, decode_chunk: int | None = None,
                 page_size: int | None = None,
                 num_pages: int | None = None,
                 prefix_cache: bool | None = None, kv_dtype: str = "bf16"):
        from ray_tpu.utils.config import get_config

        _cfg = get_config()
        if page_size is None:
            page_size = _cfg.serve_kv_page_size    # flag
        if prefix_cache is None:
            prefix_cache = _cfg.serve_prefix_cache_enabled   # flag
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype must be 'bf16' or 'int8', "
                             f"got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self.page_size = page_size
        self.max_pages_per_seq = -(-max_len // page_size)
        # default pool: half the dense equivalent — the paged layout's
        # raison d'être is NOT reserving worst-case length per slot —
        # floored so every slot can hold a minimal reservation (prompt
        # page + 1 overshoot page); without the floor, short-sequence
        # configs (max_pages_per_seq == 2) starve half of max_batch and
        # admission waits a full generation for pages, not slots
        if num_pages is not None:
            self.num_pages = num_pages
        else:
            half_dense = max_batch * self.max_pages_per_seq // 2
            floor = max_batch * min(2, self.max_pages_per_seq)
            self.num_pages = max(half_dense, floor)
        self._prefix_enabled = prefix_cache
        super().__init__(cfg, params, max_batch=max_batch,
                         max_len=max_len, decode_chunk=decode_chunk)
        # prefix-cache digest publishing (serve/prefix_router.py): the
        # engine periodically drops a compact digest — chained full-page
        # hashes + pool occupancy — into the process annex registry;
        # the metrics pusher piggybacks it to the GCS and handles route
        # repeat-prefix traffic to the replica already holding the pages
        self._digest_enabled = (self._prefix_enabled
                                and _cfg.serve_prefix_routing_enabled)
        self._digest_interval = float(_cfg.serve_digest_publish_interval_s)
        self._digest_t = 0.0

    # -- device state ------------------------------------------------------

    def _setup_device_state(self):
        cfg = self.cfg
        nkv = getattr(cfg, "n_kv_heads", None) or cfg.n_heads
        shape = (cfg.n_layers, self.num_pages, self.page_size, nkv,
                 cfg.head_dim)
        page_dtype = jnp.int8 if self.kv_dtype == "int8" else jnp.bfloat16
        self._k_pages = jnp.zeros(shape, page_dtype)
        self._v_pages = jnp.zeros(shape, page_dtype)
        # per-token-per-head dequant scales (int8 mode; tiny dummies in
        # bf16 mode so every program shares one signature/donation set)
        scale_shape = (shape[:-1] if self.kv_dtype == "int8"
                       else (cfg.n_layers, 1, 1, 1))
        self._k_scale = jnp.ones(scale_shape, jnp.float32)
        self._v_scale = jnp.ones(scale_shape, jnp.float32)
        self._table = np.full((self.max_batch, self.max_pages_per_seq),
                              -1, np.int32)
        self._alloc = PageAllocator(self.num_pages)
        # deferred page frees: (slot_pages, syncs_remaining) — a chunk
        # dispatched before the retirement was observed may still write
        # into the retired slot's own pages; they return to the free
        # list only after two chunk syncs have drained the pipeline
        self._deferred_free: list[list[int]] = []
        self._decode_cache: dict[tuple[int, int], object] = {}
        self._prefill_cache: dict[int, object] = {}
        # prefix cache state: shared (read-only, refcounted) pages per
        # slot, the slot's cached-prefix token count, and the full-page
        # hash chain awaiting registration after its prefill dispatch
        self._prefix = PrefixCache()
        self._shared: dict[int, list[int]] = {}
        self._prefix_len = np.zeros((self.max_batch,), np.int32)
        self._pending_hashes: dict[int, list[bytes]] = {}

    def _decode_paged(self, chunk: int, pages_bucket: int):
        key = (chunk, pages_bucket)
        fn = self._decode_cache.get(key)
        if fn is None:
            fn = jax.jit(
                partial(self._paged_decode_impl, self.cfg, chunk=chunk,
                        page_size=self.page_size,
                        quantized=self.kv_dtype == "int8"),
                donate_argnums=(1, 2, 3, 4))
            self._decode_cache[key] = fn
        return fn

    def _prefill_paged(self, window_pages: int):
        """Prefill program gathering a ``window_pages``-page KV window —
        bucketed like decode so a short-prompt batch reads a fraction of
        the full window's KV bytes (the window must cover every row's
        start + suffix)."""
        fn = self._prefill_cache.get(window_pages)
        if fn is None:
            fn = jax.jit(
                partial(self._paged_prefill_impl, self.cfg,
                        page_size=self.page_size,
                        quantized=self.kv_dtype == "int8"),
                donate_argnums=(1, 2, 3, 4))
            self._prefill_cache[window_pages] = fn
        return fn

    def _window_pages(self, max_covered: int) -> int:
        """Power-of-two page count covering ``max_covered`` tokens,
        clamped to the table width."""
        need = max(1, -(-max_covered // self.page_size))
        return min(_bucket(need, minimum=1), self.max_pages_per_seq)

    # -- jitted programs ---------------------------------------------------

    @staticmethod
    def _paged_decode_impl(cfg, params, k_pages, v_pages, k_scale,
                           v_scale, table, tokens, lengths, active,
                           temps, key, *, chunk, page_size, quantized):
        """``chunk`` decode steps over every slot; KV pages written and
        gathered through the (bucketed) page table [B, PB]. In int8
        mode (``quantized``) writes quantize per token+head and gathers
        dequantize against the scale pages — half the KV bytes per
        step."""
        num_pages = k_pages.shape[1]
        b, pb = table.shape
        s = pb * page_size
        scale = cfg.head_dim ** -0.5
        table_c = jnp.maximum(table, 0)

        def one_step(carry, _):
            k_pages, v_pages, k_scale, v_scale, toks, lens, key = carry
            key, sub = jax.random.split(key)
            pos = jnp.where(active, lens, 0)                    # [B]
            x = params["embedding"][toks[:, None]]              # [B,1,d]
            sin, cos = rope_sin_cos(pos[:, None], cfg.head_dim,
                                    theta=cfg.rope_theta)
            # per-slot write target for this token
            pidx = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            # holes (beyond reserved pages) drop; inactive slots drop too
            pidx = jnp.where((pidx >= 0) & active, pidx, num_pages)
            ip = pos % page_size

            def block(x, xs):
                p, kp, vp, ks, vs = xs
                h = rms_norm(x, p["attn_norm"], eps=cfg.rms_eps)
                q = (h @ p["wq"]).reshape(b, 1, cfg.n_heads, cfg.head_dim)
                k = (h @ p["wk"]).reshape(b, 1, cfg.n_kv_heads,
                                          cfg.head_dim)
                v = (h @ p["wv"]).reshape(b, 1, cfg.n_kv_heads,
                                          cfg.head_dim)
                q = apply_rope(q, sin, cos)
                k = apply_rope(k, sin, cos)
                kp, vp, ks, vs, kg, vg = _write_gather_kv(
                    kp, vp, ks, vs, k[:, 0], v[:, 0], pidx, ip,
                    table_c, quantized)
                # this slot's window [B, PB, page, nkv, hd]
                kg = kg.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
                vg = vg.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
                attn = _cached_attention(q, kg, vg, pos, scale=scale)
                x = x + attn.reshape(b, 1, -1) @ p["wo"]
                h = rms_norm(x, p["mlp_norm"], eps=cfg.rms_eps)
                gated = jax.nn.silu(h @ p["w_gate"]) * (h @ p["w_up"])
                x = x + gated @ p["w_down"]
                return x, (kp, vp, ks, vs)

            x, (k_pages, v_pages, k_scale, v_scale) = jax.lax.scan(
                block, x,
                (params["blocks"], k_pages, v_pages, k_scale, v_scale))
            x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)[:, 0]
            head = llama.lm_head_weights(cfg, params)
            logits = jnp.einsum("bd,dv->bv", x, head,
                                preferred_element_type=jnp.float32)
            nxt = select_tokens(logits, temps, sub)
            lens = jnp.where(active, lens + 1, lens)
            return (k_pages, v_pages, k_scale, v_scale, nxt, lens,
                    key), nxt

        (k_pages, v_pages, k_scale, v_scale, _, lens, _), toks = \
            jax.lax.scan(
                one_step,
                (k_pages, v_pages, k_scale, v_scale, tokens, lengths,
                 key), None, length=chunk)
        # merged device-resident last-token vector (see llm._decode_impl)
        new_last = jnp.where(active, toks[-1], tokens)
        return k_pages, v_pages, k_scale, v_scale, toks, lens, new_last

    @staticmethod
    def _paged_prefill_impl(cfg, params, k_pages, v_pages, k_scale,
                            v_scale, table_rows, tokens, slens, starts,
                            temps, key, *, page_size, quantized):
        """Prefill ``n`` prompt SUFFIXES (one padded bucket) into pages
        and sample each row's first token. ``tokens`` holds only the
        tokens past each row's cached prefix (``starts`` absolute
        offsets; 0 = no prefix reuse, the plain prefill). Suffix KV is
        written into the pages first, then attention runs over the
        row's whole gathered page window, so suffix queries see the
        reused prefix KV exactly as the original prompt computed it.
        table_rows: [n, max_pages_per_seq]."""
        num_pages = k_pages.shape[1]
        n, t = tokens.shape
        mp = table_rows.shape[1]
        s = mp * page_size
        scale = cfg.head_dim ** -0.5
        x = params["embedding"][tokens]
        rel = jnp.arange(t, dtype=jnp.int32)
        positions = starts[:, None] + rel[None, :]            # [n, T]
        sin, cos = rope_sin_cos(positions, cfg.head_dim,
                                theta=cfg.rope_theta)
        pidx_all = jnp.take_along_axis(
            table_rows, positions // page_size, axis=1)       # [n, T]
        valid = rel[None, :] < slens[:, None]                 # [n, T]
        pidx_all = jnp.where((pidx_all >= 0) & valid, pidx_all,
                             num_pages)
        ip_all = positions % page_size
        table_c = jnp.maximum(table_rows, 0)

        def block(x, xs):
            p, kp, vp, ks, vs = xs
            h = rms_norm(x, p["attn_norm"], eps=cfg.rms_eps)
            q = (h @ p["wq"]).reshape(n, t, cfg.n_heads, cfg.head_dim)
            k = (h @ p["wk"]).reshape(n, t, cfg.n_kv_heads, cfg.head_dim)
            v = (h @ p["wv"]).reshape(n, t, cfg.n_kv_heads, cfg.head_dim)
            q = apply_rope(q, sin, cos)
            k = apply_rope(k, sin, cos)
            kp, vp, ks, vs, kg, vg = _write_gather_kv(
                kp, vp, ks, vs, k, v, pidx_all, ip_all, table_c,
                quantized)
            # gather the whole window AFTER the suffix writes: queries
            # attend over cached prefix + their own fresh KV; positions
            # beyond start+i are masked causally, stale page contents
            # beyond the prompt never influence the result
            kg = kg.reshape(n, s, cfg.n_kv_heads, cfg.head_dim)
            vg = vg.reshape(n, s, cfg.n_kv_heads, cfg.head_dim)
            attn = _cached_attention(q, kg, vg, starts, scale=scale)
            x = x + attn.reshape(n, t, -1) @ p["wo"]
            h = rms_norm(x, p["mlp_norm"], eps=cfg.rms_eps)
            gated = jax.nn.silu(h @ p["w_gate"]) * (h @ p["w_up"])
            x = x + gated @ p["w_down"]
            return x, (kp, vp, ks, vs)

        x, (k_pages, v_pages, k_scale, v_scale) = jax.lax.scan(
            block, x, (params["blocks"], k_pages, v_pages, k_scale,
                       v_scale))
        x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)
        x = jnp.take_along_axis(
            x, (slens - 1)[:, None, None], axis=1).squeeze(1)
        head = llama.lm_head_weights(cfg, params)
        logits = jnp.einsum("bd,dv->bv", x, head,
                            preferred_element_type=jnp.float32)
        first = select_tokens(logits, temps, key)
        return k_pages, v_pages, k_scale, v_scale, first

    # -- engine integration ------------------------------------------------

    def _pages_bucket(self) -> int:
        """Power-of-two page count covering every live slot's RESERVED
        pages — exclusive AND shared-prefix (chained chunks may run
        ahead of the host's view of lengths, but never past the
        reservation)."""
        owned = [len(self._alloc.owned.get(i, ()))
                 + len(self._shared.get(i, ()))
                 for i, r in enumerate(self._active) if r is not None]
        need = max(owned) if owned else 1
        pb = 1
        while pb < need:
            pb *= 2
        return min(pb, self.max_pages_per_seq)

    def _decode_call(self, chunk: int, last_tok, dev):
        pb = self._pages_bucket()
        fn = self._decode_paged(chunk, pb)
        key = ("table", pb)
        if key not in dev:
            # sliced page table uploads only on admission/retirement
            # (the _device_inputs rebuild drops stale entries). The
            # explicit host COPY matters: jnp.asarray may transfer
            # asynchronously from the numpy buffer, and a retirement
            # writing table[slot] = -1 mid-transfer would hand the
            # in-flight chunk a torn table
            dev[key] = jnp.asarray(self._table[:, :pb].copy())
        (self._k_pages, self._v_pages, self._k_scale, self._v_scale,
         toks, lens, new_last) = fn(
            self.params, self._k_pages, self._v_pages, self._k_scale,
            self._v_scale, dev[key], last_tok, dev["lens"],
            dev["active"], dev["temps"], self._next_key(),
        )
        return toks, lens, new_last

    def _reserve_slot_resources(self, req, slot: int) -> bool:
        """Reserve-on-admit: pages for prompt + token budget + one page
        of chained-dispatch overshoot; exhaustion = backpressure (the
        base _admit requeues the request until pages free up).

        With the prefix cache, cached full-prefix pages are mapped
        read-only into the slot's table (refcounted, never re-written:
        suffix writes start at the first non-reused page boundary and
        decode writes past the prompt) and only the remainder is
        allocated fresh; idle cached pages are LRU-evicted into the
        free list when admission needs the space."""
        plen = len(req.prompt)
        budget = min(plen + req.max_new_tokens, self.max_len)
        pages = min(-(-budget // self.page_size) + 1,
                    self.max_pages_per_seq)
        if pages > self.num_pages:
            # can NEVER fit, even with the pool empty: reject now (the
            # base _admit turns req.error into a terminated stream)
            req.error = MemoryError(
                f"request needs {pages} KV pages "
                f"(prompt {plen} + budget {req.max_new_tokens}) but the "
                f"pool holds only {self.num_pages}; raise num_pages or "
                f"lower max_new_tokens")
            return False
        hits: list[int] = []
        hashes: list[bytes] = []
        if self._prefix_enabled:
            prompt = np.asarray(req.prompt, np.int32)
            hashes = page_hashes(prompt, self.page_size)
            # keep at least one suffix token: the first output token is
            # sampled from the suffix prefill's logits
            max_reuse = (plen - 1) // self.page_size
            hits = self._prefix.acquire(hashes[:max_reuse])
        n_fresh = pages - len(hits)
        if n_fresh > len(self._alloc.free) and self._deferred_free:
            # Deferred frees are reclaimable for a NEW admission: the
            # prefill it dispatches is ordered AFTER every in-flight
            # chunk on the device stream, and prefill + decode write
            # each page position before the causal mask exposes it, so
            # a stale in-flight write to a reclaimed page is always
            # overwritten before any read. The sync-count deferral only
            # protects the no-reuse window; claiming under pressure
            # saves up to two chunk periods of admission latency — the
            # dominant queue_wait term when the pool runs tight.
            self._age_deferred_frees(drain_all=True)
        if n_fresh > len(self._alloc.free) + self._prefix.evictable():
            self._prefix.release(hits)   # nothing dispatched yet
            return False
        if n_fresh > len(self._alloc.free):
            self._alloc.free.extend(
                self._prefix.evict(n_fresh - len(self._alloc.free)))
        page_ids = self._alloc.alloc(slot, n_fresh)
        self._table[slot, :] = -1
        if hits:
            self._table[slot, :len(hits)] = hits
        self._table[slot, len(hits):pages] = page_ids
        self._shared[slot] = list(hits)
        self._prefix_len[slot] = len(hits) * self.page_size
        if self._prefix_enabled:
            self._pending_hashes[slot] = hashes
        return True

    def _pack_admit(self, req, slot: int, plen: int) -> tuple:
        """Pack only the SUFFIX past the slot's cached prefix — a
        shared-prefix request prefills (and buckets) just its tail."""
        start = int(self._prefix_len[slot])
        suffix = np.asarray(req.prompt, np.int32)[start:]
        bucket = min(_bucket(len(suffix)), self.max_len)
        padded = np.zeros((bucket,), np.int32)
        padded[:len(suffix)] = suffix
        return (req, slot, plen, padded)

    def _dispatch_prefill(self, part: list, bucket: int):
        tokens = jnp.asarray(np.stack([it[3] for it in part]))
        starts_np = np.array([self._prefix_len[it[1]] for it in part],
                             np.int32)
        slens_np = np.array([it[2] for it in part], np.int32) - starts_np
        wp = self._window_pages(int((starts_np + slens_np).max()))
        prefill = self._prefill_paged(wp)
        slens = jnp.asarray(slens_np)
        rows = jnp.asarray(np.stack(
            [self._table[it[1]][:wp] for it in part]))
        temps = jnp.asarray(np.array(
            [it[0].temperature for it in part], np.float32))
        (self._k_pages, self._v_pages, self._k_scale, self._v_scale,
         firsts) = prefill(
            self.params, self._k_pages, self._v_pages, self._k_scale,
            self._v_scale, rows, tokens, slens, jnp.asarray(starts_np),
            temps, self._next_key())
        # the dispatch above is what makes each slot's full prompt pages
        # valid on device: REGISTER them in the prefix cache now — any
        # future admission's prefill program runs after this one on the
        # device stream, so a reader can never observe unwritten pages
        for req, slot, plen, _ in part:
            self._register_prefix(slot, plen)
        return firsts

    def _register_prefix(self, slot: int, plen: int):
        """Move this slot's freshly prefilled FULL prompt pages into the
        prefix cache (reused pages are already registered). A page that
        becomes cached is reclassified exclusive -> shared so retirement
        releases a reference instead of freeing it."""
        hashes = self._pending_hashes.pop(slot, [])
        if not hashes:
            return
        owned = self._alloc.owned.get(slot, [])
        shared = self._shared.setdefault(slot, [])
        n_shared = len(shared)
        for i in range(n_shared, min(len(hashes), plen // self.page_size)):
            page = int(self._table[slot, i])
            if page < 0 or not self._prefix.insert(hashes[i], page):
                # hash raced in from an identical concurrent prompt:
                # keep our copy exclusive (freed normally at retirement)
                continue
            if page in owned:
                owned.remove(page)
            shared.append(page)
            self._prefix.ref(page)

    def _publish_digest(self, force: bool = False):
        """Drop this replica's prefix-cache digest into the process
        annex registry (throttled; the pusher ships it). Engine-thread
        only — ``_by_hash`` has a single mutator."""
        if not self._digest_enabled:
            return
        import time as _time
        now = _time.monotonic()
        if not force and now - self._digest_t < self._digest_interval:
            return
        self._digest_t = now
        from ray_tpu.runtime import metrics_plane as _mp
        hashes = [int.from_bytes(h[:8], "little")
                  for h in list(self._prefix._by_hash)]
        _mp.set_annex(f"serve/prefix_digest/{self.replica_tag}", {
            "tag": self.replica_tag,
            "deployment": self.deployment_name,
            "page_size": self.page_size,
            "hashes": hashes,
            "kv_free": len(self._alloc.free),
            "kv_total": self.num_pages,
        })

    def _on_slot_retired(self, slot: int):
        super()._on_slot_retired(slot)   # marks device inputs dirty
        # a chunk dispatched before this retirement was observed may
        # still write into the slot's own (reserved) pages: defer the
        # free by two chunk syncs. Shared prefix pages are released
        # immediately — nothing ever WRITES them (suffix and decode
        # positions lie past the prefix), and a stale in-flight read of
        # a page later evicted + rewritten only feeds tokens the
        # retired slot already discards.
        pages = self._alloc.owned.pop(slot, [])
        shared = self._shared.pop(slot, [])
        self._pending_hashes.pop(slot, None)
        self._table[slot, :] = -1
        self._prefix_len[slot] = 0
        if shared:
            self._prefix.release(shared)
        if pages:
            self._deferred_free.append([2, pages])

    def _age_deferred_frees(self, drain_all: bool = False):
        still = []
        for entry in self._deferred_free:
            entry[0] -= 1
            if drain_all or entry[0] <= 0:
                self._alloc.free.extend(entry[1])
            else:
                still.append(entry)
        self._deferred_free = still

    def _emit_chunk(self, toks_np, active_idx, gens):
        super()._emit_chunk(toks_np, active_idx, gens)
        # one chunk sync elapsed: age the deferred frees
        self._age_deferred_frees()
        self._publish_digest()

    def _on_idle(self):
        # no active slots and nothing in flight: every dispatched chunk
        # has synced, so deferred frees cannot race anything — release
        # them all (otherwise pages retired on the last emit before an
        # idle period would strand and deadlock page backpressure)
        if self._deferred_free:
            self._age_deferred_frees(drain_all=True)

    def warmup_prefix(self, prefix_len: int, tail_len: int,
                      max_n: int | None = None):
        """Compile the SUFFIX prefill variants that prefix-cache hits
        dispatch (tail bucket + the window covering prefix+tail), so a
        deployment with a known system-prompt shape doesn't pay XLA
        compilation inside the first shared-prefix request's TTFT.
        ``warmup`` alone only covers the cold (starts=0) path."""
        bucket = min(_bucket(tail_len), self.max_len)
        wp = self._window_pages(prefix_len + bucket)
        prefill = self._prefill_paged(wp)
        n = 1
        top = max_n if max_n is not None else self.max_batch
        while n <= top:
            rows = jnp.full((n, wp), -1, jnp.int32)
            (self._k_pages, self._v_pages, self._k_scale,
             self._v_scale, firsts) = prefill(
                self.params, self._k_pages, self._v_pages,
                self._k_scale, self._v_scale, rows,
                jnp.zeros((n, bucket), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.full((n,), prefix_len, jnp.int32),
                jnp.zeros((n,), jnp.float32), self._next_key())
            np.asarray(firsts)
            n *= 2

    def warmup(self, prompt_len: int):
        """Compile the prefill program (each power-of-two group size at
        this bucket) and the decode programs at every pages-bucket a
        run can touch. For shared-prefix workloads also call
        ``warmup_prefix`` with the expected (prefix, tail) shape."""
        bucket = min(_bucket(prompt_len), self.max_len)
        wp = self._window_pages(bucket)
        prefill = self._prefill_paged(wp)
        if self._last_dev is None:
            self._last_dev = jnp.asarray(self._last_tok)
        n = 1
        while n <= self.max_batch:
            rows = jnp.full((n, wp), -1, jnp.int32)
            (self._k_pages, self._v_pages, self._k_scale,
             self._v_scale, firsts) = prefill(
                self.params, self._k_pages, self._v_pages,
                self._k_scale, self._v_scale, rows,
                jnp.zeros((n, bucket), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n,), jnp.float32), self._next_key())
            # warm the firsts scatter at this group size (it
            # specializes per slots-shape; compiling inside _admit
            # stalls the loop ~0.5s — measured)
            self._last_dev = self._scatter_fn(
                self._last_dev, jnp.arange(n, dtype=jnp.int32), firsts)
            np.asarray(firsts)
            n *= 2
        self._last_dev = jnp.asarray(self._last_tok)
        active = jnp.zeros((self.max_batch,), bool)
        # every pages-bucket a run can touch: powers of two PLUS the
        # non-power-of-two cap (_pages_bucket clamps to it — e.g.
        # max_pages_per_seq=6 serves buckets {1,2,4,6})
        buckets = []
        pb = 1
        while pb < self.max_pages_per_seq:
            buckets.append(pb)
            pb *= 2
        buckets.append(self.max_pages_per_seq)
        for pb in buckets:
            for chunk in {self.decode_chunk, self._drain_chunk}:
                fn = self._decode_paged(chunk, pb)
                (self._k_pages, self._v_pages, self._k_scale,
                 self._v_scale, toks, _, _) = fn(
                    self.params, self._k_pages, self._v_pages,
                    self._k_scale, self._v_scale,
                    jnp.full((self.max_batch, pb), -1, jnp.int32),
                    jnp.zeros((self.max_batch,), jnp.int32),
                    jnp.zeros((self.max_batch,), jnp.int32), active,
                    jnp.zeros((self.max_batch,), jnp.float32),
                    self._next_key())
                np.asarray(toks)
        self._lengths[:] = 0
        self._last_tok[:] = 0

    def stats(self) -> dict:
        out = super().stats()
        out["kv_pages_total"] = self.num_pages
        out["kv_pages_free"] = len(self._alloc.free)
        # feed the metrics plane: pool occupancy + prefix-cache hit
        # counters ride the process's next pushed delta frame
        from ray_tpu.util import metrics as _m
        if _m.enabled():
            g = _m.gauge("ray_tpu_serve_kv_pages",
                         "paged-KV pool size by state",
                         tag_keys=("state", "deployment", "replica"))
            base = {"deployment": self.deployment_name,
                    "replica": self.replica_tag}
            g.set(out["kv_pages_free"], tags={"state": "free", **base})
            g.set(self.num_pages, tags={"state": "total", **base})
        self._publish_digest(force=True)
        out["prefix_cache"] = {
            "enabled": self._prefix_enabled,
            "hit_pages": self._prefix.hit_pages,
            "miss_pages": self._prefix.miss_pages,
            "cached_idle_pages": self._prefix.evictable(),
        }
        out["kv_dtype"] = self.kv_dtype
        scale_bytes = (self._k_scale.size * 4 * 2
                       if self.kv_dtype == "int8" else 0)
        out["kv_pages_bytes"] = int(
            self._k_pages.size * self._k_pages.dtype.itemsize * 2
            + scale_bytes)   # K+V pages (+ dequant scales in int8 mode)
        dense = (self.cfg.n_layers * self.max_batch * self.max_len
                 * self._k_pages.shape[3] * self._k_pages.shape[4]
                 * 2 * 2)
        out["kv_dense_equiv_bytes"] = int(dense)
        return out
