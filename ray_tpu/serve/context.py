"""Replica context: which deployment/replica the current code runs in.

Reference analog: ``serve.get_replica_context()``
(``serve/context.py`` — ReplicaContext dataclass). The hosting
``_Replica`` actor sets the context on its own thread before
constructing the user deployment object, so engine code (e.g.
``serve/llm.py``) can tag its metrics series and prefix-cache digests
with the deployment name and a stable replica tag. Thread-local: in
local mode several replicas share one process, and each actor
constructs its body on its own thread."""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicaContext:
    deployment: str
    replica_tag: str


_local = threading.local()


def set_replica_context(deployment: str | None,
                        replica_tag: str | None) -> None:
    """Install (or clear, with Nones) the calling thread's context."""
    if deployment is None or replica_tag is None:
        _local.ctx = None
    else:
        _local.ctx = ReplicaContext(deployment=str(deployment),
                                    replica_tag=str(replica_tag))


def get_replica_context() -> ReplicaContext | None:
    return getattr(_local, "ctx", None)
