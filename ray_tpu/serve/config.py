"""Deployment configuration (reference: ``serve/config.py`` +
``serve/schema.py`` pydantic models, collapsed to dataclasses)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # -- metrics-driven policy (serve/controller.py) ------------------
    # "metrics" consumes pushed queue_wait / ongoing / KV-occupancy
    # windows from the cluster metrics plane and degrades to the
    # original polled per-replica loop whenever those windows are
    # missing or stale (partitioned metrics plane, cold deployment);
    # "polled" pins the original behavior.
    policy: str = "metrics"
    # how far back pushed windows are read; also the staleness horizon
    # past which the policy declares the plane partitioned
    metrics_window_s: float = 3.0
    # upscale when the windowed queue_wait p50 exceeds this (seconds),
    # even if per-replica ongoing still looks healthy — queue growth is
    # the leading indicator the polled loop cannot see
    upscale_queue_wait_s: float = 0.25
    # upscale when cluster KV-page occupancy exceeds this fraction
    # (paged LLM replicas: admission backpressure is imminent)
    kv_upscale_occupancy: float = 0.9


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    autoscaling: AutoscalingConfig | None = None
    user_config: dict = field(default_factory=dict)
    resources_per_replica: dict = field(default_factory=dict)
