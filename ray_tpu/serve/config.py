"""Deployment configuration (reference: ``serve/config.py`` +
``serve/schema.py`` pydantic models, collapsed to dataclasses)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    autoscaling: AutoscalingConfig | None = None
    user_config: dict = field(default_factory=dict)
    resources_per_replica: dict = field(default_factory=dict)
