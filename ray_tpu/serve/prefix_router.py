"""Prefix-affinity replica routing for serve handles.

Reference analog: none in the reference repo (its router balances on
queue lengths only — ``serve/_private/router.py`` PowerOfTwoChoices);
the design here follows production inference routers (sticky-session /
prefix-cache-aware scheduling) adapted to this repo's metrics plane.

Each ``PagedLLMEngine`` replica periodically publishes a compact
PREFIX DIGEST — the chained full-page hashes currently resident in its
prefix cache (truncated to 8 bytes) plus KV-pool occupancy — as a
metric ANNEX piggybacked on its pusher's delta frames
(``runtime/metrics_plane.py``). The handle pulls the digests (throttled,
``serve_digest_publish_interval_s``) via
``util.state.cluster_metric_annexes`` and scores candidate replicas by
the longest run of LEADING request pages already cached there. Because
page hashes are chained (hash_i covers tokens of pages 0..i), a single
set-membership hit at rank i proves the whole prefix matches — the
score is simply the length of the leading run present in the digest.

Routing decision: highest score wins when any score > 0 (ties break on
fewer outstanding requests, then more free KV pages); all-zero scores
return ``None`` and the handle falls back to its power-of-two-choices
pick. Digests older than ``serve_digest_ttl_s`` are ignored, so a
partitioned metrics plane degrades to plain p2c rather than routing on
stale affinity.
"""

from __future__ import annotations

import time

from ray_tpu.ops.paged_attention import page_hashes

DIGEST_PREFIX = "serve/prefix_digest/"


def digest_hashes(tokens, page_size: int) -> list[int]:
    """The 8-byte-truncated chained page hashes a replica's digest
    would hold for ``tokens`` — the router-side mirror of the engine's
    ``page_hashes`` + truncation."""
    return [int.from_bytes(h[:8], "little")
            for h in page_hashes(list(tokens), page_size)]


class PrefixRouter:
    """Holds the freshest digest per replica tag and scores candidates
    for a request's prompt tokens. All state is soft: losing it costs
    cache locality, never correctness."""

    def __init__(self, ttl_s: float | None = None):
        from ray_tpu.utils.config import get_config

        self._ttl = (ttl_s if ttl_s is not None
                     else get_config().serve_digest_ttl_s)
        # tag -> {ts, page_size, hashes(set), kv_free, kv_total}
        self._digests: dict[str, dict] = {}
        # chain cache for the current pick() call only (page_size ->
        # hash list); prompts differ per request, so no cross-call reuse
        self.hits = 0
        self.fallbacks = 0

    # -- digest ingest -------------------------------------------------

    def ingest(self, annexes: list) -> None:
        """Feed annex records (``cluster_metric_annexes`` output).
        Latest-wins per replica tag; non-digest records are skipped."""
        for rec in annexes or ():
            payload = rec.get("payload") or {}
            tag = payload.get("tag")
            if not tag or "hashes" not in payload:
                continue
            cur = self._digests.get(tag)
            ts = float(rec.get("ts") or 0.0)
            if cur is not None and cur["ts"] > ts:
                continue
            self._digests[tag] = {
                "ts": ts,
                "page_size": int(payload.get("page_size") or 0),
                "hashes": set(payload["hashes"]),
                "kv_free": int(payload.get("kv_free") or 0),
                "kv_total": int(payload.get("kv_total") or 0),
            }

    def forget(self, tag: str) -> None:
        self._digests.pop(tag, None)

    def digest_count(self) -> int:
        return len(self._digests)

    # -- scoring -------------------------------------------------------

    def score(self, tokens, tag: str, now: float | None = None) -> int:
        """Number of leading full pages of ``tokens`` cached at
        ``tag`` (0 for unknown/stale digests or page-size mismatch)."""
        d = self._digests.get(tag)
        now = time.time() if now is None else now
        if d is None or not d["page_size"] or now - d["ts"] > self._ttl:
            return 0
        chain = digest_hashes(tokens, d["page_size"])
        run = 0
        for h in chain:
            if h not in d["hashes"]:
                break
            run += 1
        return run

    def pick(self, tokens, candidates: dict) -> str | None:
        """Best replica tag for ``tokens`` among ``candidates``
        ({tag: outstanding count}), or None when no candidate holds any
        matching prefix (caller falls back to p2c). The score is in
        PAGES, so one hit already amortizes a whole page of prefill."""
        if not tokens or not candidates or not self._digests:
            return None
        now = time.time()
        best_tag = None
        best = (0, 0, 0)    # (score, -outstanding, kv_free)
        chains: dict[int, list[int]] = {}   # hash once per page size
        for tag, outstanding in candidates.items():
            d = self._digests.get(tag)
            if (d is None or not d["page_size"]
                    or now - d["ts"] > self._ttl):
                continue
            chain = chains.get(d["page_size"])
            if chain is None:
                chain = chains[d["page_size"]] = digest_hashes(
                    tokens, d["page_size"])
            s = 0
            for h in chain:
                if h not in d["hashes"]:
                    break
                s += 1
            if s <= 0:
                continue
            key = (s, -int(outstanding), d["kv_free"])
            if key > best:
                best = key
                best_tag = tag
        if best_tag is None:
            self.fallbacks += 1
        else:
            self.hits += 1
        # flight-recorder breadcrumb: routing decisions are the first
        # thing to read when a serve trace shows a cold-cache prefill
        from ray_tpu.util import tracing as _tracing
        _tracing.record_event(
            "prefix_router.pick",
            hit=best_tag is not None,
            tag=best_tag,
            score_pages=best[0] if best_tag is not None else 0)
        return best_tag
