"""ASGI mounting + gRPC ingress for Serve.

Reference analogs: deployments mounting FastAPI apps
(``@serve.ingress(app)``, ``python/ray/serve/api.py``) and the gRPC
proxy path (``serve/_private/proxy.py:375`` + ``grpc_util.py``).

- :func:`ingress` — wrap ANY ASGI application (FastAPI, Starlette, or a
  bare ``async def app(scope, receive, send)``) so a deployment serves
  it: the replica drives the ASGI protocol directly on a private event
  loop (no uvicorn needed), and the HTTP proxy forwards the raw request
  (method/path/headers/body) instead of the fixed JSON shape.
- :func:`start_grpc_proxy` — a generic gRPC ingress: unary call to
  ``/ray_tpu.serve.Serve/<deployment>`` with a JSON-bytes payload routes
  to that deployment, mirroring the HTTP proxy's routing. Generic
  handlers keep it proto-stub-free (clients use
  ``channel.unary_unary("/ray_tpu.serve.Serve/<name>")``).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

from ray_tpu.util import metrics as _metrics

# per-request ingress timer (metrics plane): full replica-side handling
# latency of one proxied HTTP request through the ASGI app
_h_ingress = _metrics.histogram(
    "ray_tpu_serve_ingress_s",
    "replica-side ASGI ingress request handling latency").handle()


class _ASGIDriver:
    """Drives one ASGI app on a dedicated event loop thread and turns
    raw-request dicts into raw-response dicts."""

    def __init__(self, app):
        self.app = app
        self._loop = asyncio.new_event_loop()
        t = threading.Thread(target=self._loop.run_forever, daemon=True,
                             name="serve-asgi-loop")
        t.start()
        # ASGI lifespan: best-effort startup (apps that don't implement
        # it raise/ignore — both fine)
        try:
            asyncio.run_coroutine_threadsafe(
                self._lifespan("startup"), self._loop).result(timeout=10)
        except Exception:  # noqa: BLE001
            pass

    async def _lifespan(self, phase: str):
        sent = []

        async def receive():
            return {"type": f"lifespan.{phase}"}

        async def send(msg):
            sent.append(msg)

        try:
            await self.app({"type": "lifespan", "asgi": {"version": "3.0"}},
                           receive, send)
        except Exception:  # noqa: BLE001 - app has no lifespan support
            pass

    async def _run(self, request: dict) -> dict:
        body = request.get("body", b"")
        sent_body = False
        status = {"code": 500, "headers": []}
        chunks: list[bytes] = []
        done = asyncio.Event()

        async def receive():
            nonlocal sent_body
            if not sent_body:
                sent_body = True
                return {"type": "http.request", "body": body,
                        "more_body": False}
            await done.wait()           # no more input
            return {"type": "http.disconnect"}

        async def send(msg):
            if msg["type"] == "http.response.start":
                status["code"] = msg["status"]
                status["headers"] = [
                    (k.decode() if isinstance(k, bytes) else k,
                     v.decode() if isinstance(v, bytes) else v)
                    for k, v in msg.get("headers", [])]
            elif msg["type"] == "http.response.body":
                chunks.append(msg.get("body", b""))
                if not msg.get("more_body"):
                    done.set()

        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.get("method", "GET"),
            "scheme": "http",
            "path": request.get("path", "/"),
            "raw_path": request.get("path", "/").encode(),
            "root_path": "",
            "query_string": request.get("query_string", b"")
            if isinstance(request.get("query_string", b""), bytes)
            else request.get("query_string", "").encode(),
            "headers": [(k.lower().encode(), v.encode())
                        for k, v in request.get("headers", [])],
            "client": ("127.0.0.1", 0),
            "server": ("127.0.0.1", 80),
        }
        await self.app(scope, receive, send)
        done.set()
        return {"__raw__": True, "status": status["code"],
                "headers": status["headers"], "body": b"".join(chunks)}

    def handle(self, request: dict) -> dict:
        t0 = time.perf_counter()
        fut = asyncio.run_coroutine_threadsafe(self._run(request),
                                               self._loop)
        out = fut.result(timeout=request.get("timeout_s", 60))
        if _metrics.enabled():
            _h_ingress.observe(time.perf_counter() - t0)
        return out

    async def ahandle(self, request: dict) -> dict:
        """Await the app (on its dedicated loop) from ANOTHER loop,
        with the same per-request timeout the sync path enforces — a
        hung app must surface an error, not hold a concurrency slot
        forever."""
        t0 = time.perf_counter()
        fut = asyncio.run_coroutine_threadsafe(self._run(request),
                                               self._loop)
        try:
            out = await asyncio.wait_for(
                asyncio.wrap_future(fut),
                timeout=request.get("timeout_s", 60))
            if _metrics.enabled():
                _h_ingress.observe(time.perf_counter() - t0)
            return out
        except asyncio.TimeoutError:
            fut.cancel()
            raise TimeoutError(
                f"ASGI app did not answer within "
                f"{request.get('timeout_s', 60)}s") from None


def ingress(asgi_app_or_factory):
    """Class decorator: the deployment serves the given ASGI app.

    ``@serve.deployment`` + ``@serve.ingress(app)`` compose like the
    reference; the wrapped class's methods remain available for handle
    calls, while HTTP traffic hitting the proxy under
    ``/<deployment>/...`` is forwarded verbatim through the ASGI app.
    Pass either an app instance or a zero-arg factory (a factory defers
    construction to the replica — needed when the app isn't picklable).
    """

    def wrap(cls):
        class ASGIIngress(cls):
            _serve_asgi = True

            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                app = asgi_app_or_factory
                target = app() if (callable(app)
                                   and not _looks_like_asgi(app)) else app
                self._asgi_driver = _ASGIDriver(target)

            async def __call__(self, request: dict):
                # async: the replica's event loop awaits the app's own
                # loop WITHOUT blocking, so concurrent HTTP requests
                # overlap per replica (the app keeps its dedicated loop —
                # lifespan-created state stays loop-consistent)
                if isinstance(request, dict) and request.get("__raw__"):
                    return await self._asgi_driver.ahandle(request)
                # non-raw payloads (handle.call) become a POST /
                body = json.dumps(request).encode() \
                    if not isinstance(request, (bytes, bytearray)) \
                    else bytes(request)
                return await self._asgi_driver.ahandle({
                    "__raw__": True, "method": "POST", "path": "/",
                    "headers": [("content-type", "application/json")],
                    "body": body})

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = cls.__qualname__
        return ASGIIngress

    return wrap


def _looks_like_asgi(app) -> bool:
    """An ASGI app is an async callable taking (scope, receive, send) —
    distinguish it from a zero-arg factory."""
    import inspect

    fn = app if inspect.isfunction(app) or inspect.iscoroutinefunction(app) \
        else getattr(app, "__call__", None)
    if fn is None:
        return False
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return len(params) >= 3


# ---------------------------------------------------------------------------
# gRPC ingress
# ---------------------------------------------------------------------------

GRPC_SERVICE = "ray_tpu.serve.Serve"


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start the gRPC ingress; returns (server, port).

    Routing mirrors the HTTP proxy: a unary call to
    ``/ray_tpu.serve.Serve/<deployment>`` carries a JSON request as
    bytes and returns ``{"result": ...}`` JSON bytes (errors surface as
    INTERNAL/NOT_FOUND status codes). Generic handlers = no proto stubs
    to generate, any grpc client can call it.
    """
    import grpc

    from ray_tpu.serve.api import get_deployment_handle

    handles: dict = {}

    class _Router(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            method = handler_call_details.method  # /pkg.Service/Name
            parts = method.strip("/").split("/")
            if len(parts) != 2 or parts[0] != GRPC_SERVICE:
                return None
            name = parts[1]

            def unary(request_bytes, context):
                handle = handles.get(name)
                if handle is None:
                    try:
                        handle = get_deployment_handle(name)
                        handle._refresh(ttl=0)
                        handles[name] = handle
                    except Exception:  # noqa: BLE001
                        context.abort(grpc.StatusCode.NOT_FOUND,
                                      f"no deployment {name!r}")
                try:
                    payload = (json.loads(request_bytes)
                               if request_bytes else {})
                    result = handle.call(payload)
                    return json.dumps({"result": result}).encode()
                except Exception as e:  # noqa: BLE001
                    context.abort(grpc.StatusCode.INTERNAL, repr(e))

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b)

    from concurrent import futures

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
    server.add_generic_rpc_handlers((_Router(),))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


def grpc_call(port: int, deployment: str, payload: dict,
              host: str = "127.0.0.1", timeout: float = 30.0):
    """Convenience client for the generic gRPC ingress."""
    import grpc

    with grpc.insecure_channel(f"{host}:{port}") as channel:
        rpc = channel.unary_unary(
            f"/{GRPC_SERVICE}/{deployment}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        out = rpc(json.dumps(payload).encode(), timeout=timeout)
    return json.loads(out)
