"""ray-tpu CLI (reference: ``python/ray/scripts/scripts.py`` — start/stop/
status/memory/… and the state CLI ``util/state/state_cli.py``)."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def cmd_start(args):
    """Start a head node (GCS + raylet) or join an existing cluster."""
    from ray_tpu.runtime.gcs import GcsServer
    from ray_tpu.runtime.raylet import Raylet
    from ray_tpu.utils.ids import NodeID

    resources = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    if args.head:
        gcs = GcsServer(host=args.host, port=args.port).start()
        print(f"GCS listening on {gcs.address[0]}:{gcs.address[1]}")
        gcs_address = gcs.address
        labels = {"head": True}
    else:
        if not args.address:
            sys.exit("--address required for non-head nodes")
        host, _, port = args.address.rpartition(":")
        gcs_address = (host, int(port))
        labels = {}
    raylet = Raylet(
        node_id=NodeID.from_random().hex(), gcs_address=gcs_address,
        resources=resources,
        store_capacity=args.object_store_memory, labels=labels).start()
    print(f"raylet on {raylet.address[0]}:{raylet.address[1]} "
          f"(store {raylet.store_name})")
    print(f"connect with: ray_tpu.init(address="
          f"'{gcs_address[0]}:{gcs_address[1]}')")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        raylet.stop()


def _gcs_client(args):
    from ray_tpu.runtime.rpc import RpcClient

    host, _, port = args.address.rpartition(":")
    return RpcClient((host or "127.0.0.1", int(port)))


def cmd_status(args):
    client = _gcs_client(args)
    nodes = client.call("get_nodes", alive_only=False)
    res = client.call("cluster_resources")
    print(f"Nodes: {sum(1 for n in nodes if n['alive'])} alive / "
          f"{len(nodes)} total")
    print(f"Resources: {json.dumps(res['available'])} available of "
          f"{json.dumps(res['total'])}")
    for n in nodes:
        mark = "+" if n["alive"] else "-"
        print(f"  [{mark}] {n['node_id'][:12]} @ "
              f"{n['address'][0]}:{n['address'][1]} {n['resources']}")


def cmd_list(args):
    client = _gcs_client(args)
    method = {"nodes": "get_nodes", "actors": "list_actors",
              "jobs": "list_jobs", "pgs": "list_placement_groups",
              "tasks": "get_task_events"}[args.resource]
    rows = client.call(method)
    print(json.dumps(rows, indent=2, default=str))


def cmd_submit(args):
    """Run a driver script against a cluster (reference: job submit)."""
    import subprocess

    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = args.address
    sys.exit(subprocess.call([sys.executable, args.script] + args.args,
                             env=env))


def cmd_summary(args):
    """Summaries like `ray summary tasks/actors` (state CLI analog)."""
    import ray_tpu
    from ray_tpu.util import state as _state

    ray_tpu.init(address=args.address)
    out = {"cluster": _state.cluster_summary(),
           "actors": _state.summarize_actors(),
           "tasks": _state.summarize_tasks()}
    print(json.dumps(out, indent=2, default=str))


def cmd_dashboard(args):
    """Serve the observability dashboard against a cluster."""
    import ray_tpu
    from ray_tpu.dashboard import start_dashboard

    if args.address:
        ray_tpu.init(address=args.address)
    else:
        ray_tpu.init(num_cpus=args.num_cpus)
    dash = start_dashboard(host=args.host, port=args.dashboard_port)
    print(f"dashboard at {dash.url}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


def cmd_timeline(args):
    import ray_tpu

    ray_tpu.init(address=args.address)
    path = args.output or "timeline.json"
    ray_tpu.timeline(path)
    print(f"wrote chrome://tracing timeline to {path}")


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.0f} {unit}" if unit == "B"
                    else f"{n:.1f} {unit}")
        n /= 1024
    return f"{n:.1f} TiB"


def _format_table(headers, rows) -> str:
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              if rows else len(str(h)) for i, h in enumerate(headers)]
    line = "  ".join(str(h).ljust(w) for h, w in zip(headers, widths))
    out = [line, "-" * len(line)]
    for r in rows:
        out.append("  ".join(str(c).ljust(w)
                             for c, w in zip(r, widths)))
    return "\n".join(out)


def _print_table(headers, rows):
    print(_format_table(headers, rows))


def render_memory_summary(summary: dict, *, top: int = 20) -> str:
    """`ray memory`-style rendering of util.state.memory_summary().
    Returns the formatted text (callers print it)."""
    t = summary.get("totals", {})
    mode = summary.get("mode", "?")
    out = [f"======== Cluster memory summary (mode={mode}) ========"]
    if summary.get("degraded"):
        out.append(f"!! GCS unreachable — local-process answer only "
                   f"({summary['degraded']})")
    out.append(
        f"Owned: {_fmt_bytes(t.get('owned_bytes'))} across "
        f"{t.get('num_owners', 0)} owners | store allocated "
        f"{_fmt_bytes(t.get('store_allocated_bytes'))} (pinned "
        f"{_fmt_bytes(t.get('store_pinned_bytes'))}) | spilled "
        f"{_fmt_bytes(t.get('store_spilled_bytes'))} | in-flight "
        f"{_fmt_bytes(t.get('in_flight_bytes'))}")

    owners = summary.get("owners", [])
    if owners:
        out.append(f"\n--- Owners (top {min(top, len(owners))} "
                   f"by bytes) ---")
        out.append(_format_table(
            ["OWNER", "KIND", "REFS", "OBJECTS", "PINNED", "SPILLED",
             "IN-PROC"],
            [[o.get("owner", "?")[:12], o.get("kind") or "?",
              o.get("refs_held", 0), o.get("owned", 0),
              _fmt_bytes(o.get("pinned_bytes")),
              _fmt_bytes(o.get("spilled_bytes")),
              _fmt_bytes(o.get("memstore_bytes"))]
             for o in owners[:top]]))

    objs = [dict(e, owner=o.get("owner", "?"))
            for o in owners for e in o.get("top", ())]
    objs.sort(key=lambda e: -e["size_bytes"])
    if objs:
        out.append(f"\n--- Top objects (top {min(top, len(objs))}) ---")
        out.append(_format_table(
            ["OBJECT ID", "SIZE", "STATE", "OWNER", "BORROW", "PINS",
             "AGE", "CALLSITE"],
            [[e["object_id"][:16], _fmt_bytes(e["size_bytes"]),
              e.get("state", "?"), e["owner"][:12],
              e.get("borrowers") if e.get("borrowers") is not None
              else "?",
              e.get("task_pins") if e.get("task_pins") is not None
              else "?",
              f"{e.get('age_s', 0):.0f}s", e.get("callsite") or "-"]
             for e in objs[:top]]))

    nodes = summary.get("nodes", [])
    if nodes:
        out.append("\n--- Nodes ---")
        out.append(_format_table(
            ["NODE", "CAPACITY", "ALLOC", "PINNED", "CACHED", "SPILLED",
             "SPILLS", "RESTORES", "EVICT"],
            [[nd.get("node_id", "?")[:12],
              _fmt_bytes(nd.get("capacity_bytes")),
              _fmt_bytes(nd.get("allocated_bytes")),
              _fmt_bytes(nd.get("pinned_bytes")),
              _fmt_bytes(nd.get("cached_replica_bytes")),
              _fmt_bytes(nd.get("spilled_bytes")),
              "{} ({:.2f}s)".format(
                  (nd.get("spill_stats") or {}).get("num_spilled", 0),
                  (nd.get("spill_stats") or {}).get("spill_wall_s", 0)),
              "{} ({:.2f}s)".format(
                  (nd.get("spill_stats") or {}).get("num_restored", 0),
                  (nd.get("spill_stats") or {}).get("restore_wall_s",
                                                    0)),
              nd.get("num_evictions", 0)]
             for nd in nodes]))

    sites = summary.get("callsites", [])
    if sites:
        out.append("\n--- Callsites ---")
        out.append(_format_table(
            ["BYTES", "COUNT", "CALLSITE"],
            [[_fmt_bytes(c["bytes"]), c["count"], c["callsite"]]
             for c in sites[:top]]))

    for ev in summary.get("pressure", [])[-8:]:
        owners_s = ", ".join(
            f"{o[:12]}:{n}"
            for o, n in (ev.get("owners") or {}).items())
        out.append(
            f"\nmake-room on {ev.get('node_id', '?')[:12]}: requested "
            f"{_fmt_bytes(ev.get('requested'))}, spilled "
            f"{len(ev.get('spilled', ()))} objects "
            f"({_fmt_bytes(ev.get('spilled_bytes'))})"
            + (f" owned by {owners_s}" if owners_s else ""))
    return "\n".join(out)


def cmd_memory(args):
    """Ownership-attributed memory table (reference: ``ray memory``)."""
    client = _gcs_client(args)
    if getattr(args, "leaks", False):
        leaks = client.call("memory_leaks")["leaks"]
        if args.json:
            print(json.dumps(leaks, indent=2, default=str))
            return
        if not leaks:
            print("no suspected leaks")
            return
        _print_table(
            ["OBJECT ID", "SIZE", "OWNER", "AGE", "IDLE", "CALLSITE"],
            [[lk["object_id"][:16], _fmt_bytes(lk["size_bytes"]),
              lk["owner"][:12], f"{lk['age_s']:.0f}s",
              f"{lk['owner_idle_s']:.0f}s", lk.get("callsite") or "-"]
             for lk in leaks])
        return
    summary = client.call("memory_summary", top_n=args.top)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
        return
    print(render_memory_summary(summary, top=args.top))


def cmd_serve_deploy(args):
    """Apply a declarative serve config (reference: ``serve deploy``)."""
    import ray_tpu
    from ray_tpu.serve.schema import apply_config_file

    if args.address:
        ray_tpu.init(address=args.address)
    else:
        ray_tpu.init(num_cpus=args.num_cpus)
    handles = apply_config_file(args.config)
    for name in handles:
        print(f"deployed {name}")
    if args.block:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass


def cmd_serve_status(args):
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(address=args.address)
    print(json.dumps(serve.status(), indent=2, default=str))


def cmd_debug(args):
    """Attach to a remote breakpoint (reference: ``ray debug`` over
    rpdb sessions registered in the GCS KV)."""
    import ray_tpu
    from ray_tpu.util import debug as rdbg

    ray_tpu.init(address=args.address)
    sessions = rdbg.active_sessions()
    if not sessions:
        print("no active breakpoints")
        return
    if len(sessions) == 1 or args.index is not None:
        chosen = sessions[args.index or 0]
    else:
        for i, s in enumerate(sessions):
            print(f"[{i}] session {s['session_id']} pid={s['pid']} "
                  f"node={s.get('node_id', '')[:8]}")
        chosen = sessions[int(input("attach to which? "))]
    print(f"attaching to {chosen['session_id']} "
          f"({chosen['host']}:{chosen['port']}) — 'c' continues, "
          f"'q' aborts the task")
    rdbg.connect(chosen)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS host:port (non-head)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6379)
    p.add_argument("--num-cpus", type=float,
                   default=float(os.cpu_count() or 1))
    p.add_argument("--num-tpus", type=float, default=0)
    p.add_argument("--object-store-memory", type=int, default=1 << 30)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster status")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("resource",
                   choices=["nodes", "actors", "jobs", "pgs", "tasks"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("submit", help="run a script against the cluster")
    p.add_argument("--address", required=True)
    p.add_argument("script")
    p.add_argument("args", nargs="*")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("memory",
                       help="ownership-attributed memory table")
    p.add_argument("--address", required=True)
    p.add_argument("--top", type=int, default=20,
                   help="rows per table section")
    p.add_argument("--json", action="store_true",
                   help="raw summary JSON instead of tables")
    p.add_argument("--leaks", action="store_true",
                   help="suspected leaked refs only")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("summary", help="cluster/actor/task summaries")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    p.add_argument("--address", help="GCS host:port (omit for local)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--num-cpus", type=float,
                   default=float(os.cpu_count() or 1))
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("timeline", help="dump chrome://tracing timeline")
    p.add_argument("--address", required=True)
    p.add_argument("--output", "-o")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("serve-deploy",
                       help="apply a declarative serve config (YAML)")
    p.add_argument("config")
    p.add_argument("--address", help="GCS host:port (omit for local)")
    p.add_argument("--num-cpus", type=float,
                   default=float(os.cpu_count() or 1))
    p.add_argument("--block", action="store_true",
                   help="keep the process (and local cluster) alive")
    p.set_defaults(fn=cmd_serve_deploy)

    p = sub.add_parser("debug", help="attach to a remote breakpoint")
    p.add_argument("--address", default="127.0.0.1:6379")
    p.add_argument("--index", type=int, default=None,
                   help="session index (skip the picker)")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("serve-status", help="serve deployment status")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_serve_status)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
