"""Driver/worker-side cluster runtime: the core_worker analog.

Reference analog: ``src/ray/core_worker/core_worker.cc`` (SubmitTask:1878,
CreateActor:1948, SubmitActorTask:2182, Put:1141, Get:1353, Wait:1509) as
driven from ``python/ray/_private/worker.py``. Duck-types the same interface
as the in-process ``runtime.core.Runtime`` so ``ray_tpu.api`` works
unchanged in both modes.

The driver attaches its local node's shm store directly (same-host zero-copy
path), submits tasks to the local raylet (which schedules locally or spills
back through the GCS view), and resolves remote objects through the
raylet's pull-based object manager.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import cloudpickle

from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.runtime import object_codec
from ray_tpu.runtime import refcount as _refcount
from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.rpc import (
    ConnectionLost,
    ReconnectingRpcClient,
    RpcClient,
)
from ray_tpu.runtime.task_spec import TaskSpec, TaskType
from ray_tpu.util import tracing as _tracing
from ray_tpu.utils import exceptions as exc
from ray_tpu.utils.ids import ActorID, ObjectID, WorkerID


_SCALAR_TYPES = (type(None), bool, int, float, str, bytes)


class ClusterRuntime:
    """Connects ``ray_tpu.api`` to a running cluster (GCS + raylets)."""

    def __init__(self, gcs_address, raylet_address=None,
                 namespace: str | None = None,
                 log_to_driver: bool = False):
        self.gcs_address = tuple(gcs_address)
        # arm the fault-injection plane (no-op unless the config flag is
        # set) BEFORE any channel dials: a startup plan must see every
        # connection this runtime makes
        from ray_tpu.runtime import fault_injection as _fi
        _fi.maybe_init_from_config(self.gcs_address,
                                   process_label="driver")
        # reconnecting: survives a GCS restart (file-backed recovery)
        self._gcs = ReconnectingRpcClient(self.gcs_address, label="driver")
        self.caller_id = WorkerID.from_random().hex()
        # ref-counting client identity: inside a pool worker the PROCESS
        # id (the Worker's flusher owns the channel there — one client
        # per process so holder attribution is consistent); drivers use
        # their own caller id (reference: reference_count.h — per-worker
        # ownership)
        import os as _os
        self.client_id = _os.environ.get("RAY_TPU_WORKER_ID",
                                         self.caller_id)
        # Namespace for named actors (reference: worker.py:1157,1258):
        # explicit init(namespace=...), else the job's own id — two jobs
        # on one cluster never collide on actor names by default. Worker-
        # side implicit runtimes resolve the AMBIENT task namespace first
        # (runtime_context), so a job's tasks see their job's actors.
        self.namespace = namespace or f"job-{self.caller_id[:12]}"
        # choose local raylet: given address, or the head node from GCS
        if raylet_address is None:
            nodes = self._gcs.call("get_nodes", alive_only=True)
            if not nodes:
                raise RuntimeError("no alive nodes in cluster")
            head = next((n for n in nodes if n["labels"].get("head")),
                        nodes[0])
            raylet_address = head["address"]
            store_name = head["store_name"]
            self.node_id = head["node_id"]
        else:
            info = RpcClient(tuple(raylet_address),
                             label="driver").call("node_info")
            store_name = info["store_name"]
            self.node_id = info["node_id"]
        self._raylet = RpcClient(tuple(raylet_address), label="driver")
        self.store = ShmObjectStore(store_name)
        self._actor_locations: dict[str, tuple] = {}   # id -> (addr, incarnation)
        self._actor_seq: dict[str, int] = {}           # id -> next seq
        # incarnation the seq numbering was issued against — tracked
        # SEPARATELY from the location cache: evicting a cached location
        # (e.g. on a transport error) must not restart numbering at 0
        # while the worker's ordered cursor sits at N, or every later
        # call is silently deduped as stale and the actor wedges
        self._actor_seq_inc: dict[str, int] = {}
        # pipelined actor submits: id -> deque[(tasks, PendingCall, addr,
        # sent_at)] — each window entry is one BATCH frame in flight
        self._actor_windows: dict[str, deque] = {}
        self._actor_gap_fillers: dict[str, list] = {}
        self._actor_reaper_started = False
        self._seq_lock = threading.Lock()
        # submit-side coalescing: callers enqueue (task, addr) here and the
        # flusher thread packs consecutive submissions to one actor into a
        # single submit_actor_tasks frame — one pickle+syscall per BURST,
        # not per call (reference: the async gRPC CallQueue in
        # DirectActorTaskSubmitter batches sends on its io thread)
        self._actor_outbox: dict[str, list] = {}
        self._actor_unacked: dict[str, int] = {}   # flow control (tasks)
        # stuck-call watchdog tokens per actor, FIFO like the unacked
        # window (acks are in submission order, so finishing the oldest
        # n tokens on an n-task ack matches 1:1)
        self._wd_tokens: dict[str, deque] = {}
        self._outbox_cv = threading.Condition()
        # Registration coalescer (same shape as the ref flusher): N
        # create_actor calls become one register_actors frame. Anonymous
        # creations return as soon as the entry is enqueued; named ones
        # wait for their per-entry ack (name-conflict stays a
        # synchronous ValueError).
        from ray_tpu.utils.config import get_config as _gcfg0
        _pcfg = _gcfg0()
        self._reg_outbox: list[dict] = []
        self._reg_pending: set[str] = set()        # enqueued, unacked ids
        self._reg_failed: dict[str, str] = {}      # async failures by id
        self._reg_cv = threading.Condition()
        self._reg_flusher_started = False
        self._reg_linger_s = _pcfg.actor_register_linger_s
        self._reg_batch_cap = max(1, _pcfg.actor_register_batch_size)
        self._reg_window = max(1, _pcfg.actor_register_window)
        # CH_ACTOR pushed location table (reference: the core worker's
        # ActorInfoAccessor subscription — resolution is an event wait,
        # not a get_actor poll storm against the locked GCS). Only
        # top-level drivers subscribe: a pool of in-worker runtimes each
        # drinking the full actor event firehose would multiply every
        # creation flood by the worker count; workers resolve few actors
        # and keep the cached-poll path.
        self._actor_pubsub = (_pcfg.actor_pubsub_enabled
                              and "RAY_TPU_WORKER_ID" not in _os.environ)
        self._resolve_fallback_s = max(0.05, _pcfg.actor_resolve_fallback_s)
        self._resolve_timeout_s = max(1.0, _pcfg.actor_resolve_timeout_s)
        self._actor_table: dict[str, dict] = {}
        self._actor_table_cv = threading.Condition()
        self._actor_sub = None
        self._actor_sub_lock = threading.Lock()
        self._actor_get_polls = 0   # get_actor fallback polls (tested: 0
                                    # once the pushed table is warm)
        self._named_cache: dict[str, str] = {}
        # cached per-address actor-call clients (see _actor_client)
        self._actor_clients: dict[tuple, RpcClient] = {}
        self._actor_clients_lock = threading.Lock()
        # acked-but-unresolved actor calls: the worker accepted them
        # into its queue, so the submit plane forgot them — but a crash
        # takes the queue down with the worker and nobody else will
        # ever write their return oids. The reaper sweeps this against
        # the pushed actor table and fails the refs of DEAD actors with
        # a typed ActorDiedError (actor_hex -> task_id -> (oids, inc)).
        self._actor_inflight: dict[str, dict[str, tuple]] = {}
        self._inflight_lock = threading.Lock()
        from ray_tpu.utils.config import get_config as _gc
        self._actor_client_cap = _gc().actor_client_cache_size
        self._actor_client_soft_cap = _gc().actor_client_soft_cap
        self.metrics: dict[str, Any] = {}
        # Lineage for object reconstruction (reference: ReferenceCounter
        # lineage pinning reference_count.h:67-115 + TaskManager::
        # ResubmitTask task_manager.h:234 + ObjectRecoveryManager
        # object_recovery_manager.h:41): return oid -> the wire task that
        # created it, so a lost object (its node died) can be re-computed
        # by re-running the task. Actor-task results are NOT recorded
        # (actor state is restored via actor restart, not re-execution).
        self._lineage: dict[str, dict] = {}
        self._lineage_lock = threading.Lock()
        self._reconstructing: set[str] = set()
        from ray_tpu.utils.config import get_config
        self._lineage_grace_s = get_config().lineage_resubmit_grace_s
        self._lineage_max = get_config().lineage_max_entries
        self._pending_grace_s = get_config().task_pending_resubmit_grace_s
        # Owner-side worker leases for default-strategy tasks (reference:
        # direct_task_transport.cc): direct worker push with synchronous
        # loss detection; placement-constrained tasks fall back to the
        # raylet queue via _legacy_submit.
        from ray_tpu.runtime.lease import LeaseManager
        self._closed = False
        self._fn_blobs: dict[int, tuple] = {}   # id(fn) -> (fn, blob)
        self._leases = LeaseManager(
            self._raylet,
            legacy_submit=self._legacy_submit,
            on_task_failed=self._fail_task_returns,
            on_direct_results=self._accept_direct_results,
        )
        # Worker-log echo (reference: log_monitor -> GCS pubsub ->
        # driver stdout). Only top-level drivers subscribe — nested
        # in-worker runtimes echoing would loop their own output back
        # through the capture files forever.
        self._log_sub = None
        # per-source echo rate limiter: proc -> [tokens, last, suppressed]
        self._echo_state: dict[str, list] = {}
        if log_to_driver:
            from ray_tpu.runtime.rpc import PushSubscriber

            self._log_sub = PushSubscriber(
                self.gcs_address,
                {"method": "subscribe", "channels": ["logs"]},
                self._print_worker_logs,
                reconnect=True,   # survive a GCS restart like _gcs does
                label="driver")
        # --- distributed refcounting (reference: reference_count.h:61;
        # see runtime/refcount.py): this runtime flushes the process's
        # ref deltas to the GCS and doubles as the client heartbeat that
        # scopes actor lifetimes. Inside a pool worker the Worker loop
        # already owns the process flush channel — skip ours. ---
        from ray_tpu.runtime import refcount as _refcount
        from ray_tpu.utils.config import get_config as _get_config
        _cfg = _get_config()
        self._refs = _refcount.global_counter
        self._ref_enabled = _cfg.ref_counting_enabled
        self._ref_interval = _cfg.ref_flush_interval_s
        self._ref_send_lock = threading.Lock()
        self._actor_window = _cfg.actor_submit_window
        # batched put-pin reports (see put/_put_report_loop)
        self._put_report_buf: list[tuple[str, int]] = []
        # direct results that failed placement on a full store, parked
        # for retry by the flusher (never silently dropped)
        self._direct_retry: list[tuple[str, bytes]] = []
        self._put_report_cv = threading.Condition()
        # In-process memory store for small direct task returns
        # (reference: CoreWorkerMemoryStore, memory_store.h:43): encoded
        # payloads keyed by oid hex, ZERO store/raylet/GCS traffic per
        # object. Entries are evicted when their refs die (refcount
        # release hook) and PROMOTED to the shm store the moment their
        # ref is serialized off-process (serialize hook) so remote
        # consumers always find a cluster-visible copy. Requires ref
        # counting (the death signal); disabled with it.
        self._memstore: dict[str, bytes] = {}
        self._mem_cv = threading.Condition()   # direct-result arrivals
        self._mem_arrivals = 0                 # arrival epoch (see get)
        # refs serialized off-process BEFORE their object arrived (a
        # pending task's return passed straight into another task): the
        # object must become cluster-visible the moment it lands, or the
        # consuming worker never finds it. _mem_cv guards the
        # check-miss-then-mark vs update-then-check interleavings.
        self._promote_pending: set[str] = set()
        self._use_memstore = self._ref_enabled
        self._memstore_put_limit = _cfg.max_direct_call_object_size
        # memory plane: owned-object accounting knobs (see
        # refcount.note_owned / ownership_snapshot)
        self._mem_callsite = _cfg.memory_callsite_enabled
        self._mem_annex_max = _cfg.memory_annex_max_entries
        if self._use_memstore:
            self._memstore_release_hook = self._evict_mem_objects
            self._memstore_serialize_hook = self._promote_mem_object
            self._refs.add_release_hook(self._memstore_release_hook)
            self._refs.add_serialize_hook(self._memstore_serialize_hook)
        threading.Thread(target=self._put_report_loop, daemon=True,
                         name="put-report-flusher").start()
        # a nested in-worker runtime must NOT claim: the Worker loop owns
        # the process flush channel (claim_flusher(worker_id) would
        # spuriously succeed for us since client_id == worker_id, and our
        # shutdown() would then unregister the still-running worker)
        in_worker = "RAY_TPU_WORKER_ID" in _os.environ
        self._owns_flusher = (self._ref_enabled and not in_worker
                              and _refcount.claim_flusher(self.client_id))
        if self._owns_flusher:
            try:
                self._gcs.call("register_client", client_id=self.client_id,
                               kind="driver")
            except Exception:  # noqa: BLE001 - reconnecting client retries
                pass
            threading.Thread(target=self._ref_flush_loop, daemon=True,
                             name="ref-flusher").start()
        # metrics plane: this process's registry pushes delta frames to
        # the GCS (claim machinery keeps it to ONE pusher per process —
        # a nested in-worker runtime loses the claim to the first one)
        from ray_tpu.runtime.metrics_plane import MetricsPusher
        self._metrics_pusher = MetricsPusher(
            self.gcs_address, src=self.client_id[:12],
            kind="worker" if in_worker else "driver").start()
        # memory plane: this process's ownership table rides the metric
        # frames as a live mem/owners annex (providers re-evaluate at
        # every pusher snapshot — the table is never publish-frozen)
        from ray_tpu.runtime import metrics_plane as _mp
        self._mem_annex_key = f"mem/owners/{self.client_id[:12]}"
        _kind = "worker" if in_worker else "driver"

        def _mem_owners_annex(_cid=self.client_id, _k=_kind):
            if not _refcount.is_active():
                return None
            snap = self._refs.ownership_snapshot(self._mem_annex_max)
            snap["client_id"] = _cid
            snap["kind"] = _k
            snap["pressure"] = object_codec.recent_pressure()
            return snap

        _mp.set_annex_provider(self._mem_annex_key, _mem_owners_annex)
        from ray_tpu.util import metrics as _metrics
        self._h_actor_resolve = _metrics.histogram(
            "ray_tpu_actor_resolve_s",
            "actor location resolve latency (cache misses only)").handle()

    def _print_worker_logs(self, msg: dict):
        """Echo CH_LOGS lines as ``(fn pid=N, node=M)``-prefixed output
        (reference: the driver-side worker-log echo). Lines stamped with
        another job's namespace are filtered out; unstamped lines (raw
        .out/.err crash output, pre-capture startup prints) always echo.
        A per-source token bucket keeps a log-spamming worker from
        wedging the driver's terminal — suppressed lines are summarized,
        not silently dropped."""
        import sys

        msgs = msg.get("batch") if isinstance(msg.get("batch"), list) \
            else [msg]
        for m in msgs:
            entry = m.get("entry")
            if not entry:
                continue
            node = (m.get("node_id") or "")[:8]
            proc = entry.get("proc") or "?"
            pid = entry.get("pid") or 0
            for rec in entry.get("lines", ()):
                try:
                    _off, _ts, stream, text, _trace, _task, name, job = rec
                except (TypeError, ValueError):
                    continue
                if job is not None and job != self.namespace:
                    continue
                ok, missed = self._echo_allow(proc)
                out = sys.stderr if stream == "e" else sys.stdout
                if missed:
                    print(f"({proc} pid={pid}, node={node}) "
                          f"... {missed} line(s) suppressed by the echo "
                          f"rate limit (RAY_TPU_LOG_ECHO_RATE_LINES_S)",
                          file=out)
                if not ok:
                    continue
                fn = name or proc
                print(f"({fn} pid={pid}, node={node}) {text}", file=out)

    def _echo_allow(self, proc: str) -> tuple:
        """Token-bucket admission for one source; returns (allowed,
        suppressed_count_to_report)."""
        from ray_tpu.utils.config import get_config

        rate = float(get_config().log_echo_rate_lines_s)
        if rate <= 0:   # 0 disables the limiter
            return True, 0
        now = time.monotonic()
        st = self._echo_state.get(proc)
        if st is None:
            if len(self._echo_state) > 512:   # dead-proc churn bound
                self._echo_state.clear()
            st = self._echo_state[proc] = [rate, now, 0]
        st[0] = min(rate, st[0] + (now - st[1]) * rate)
        st[1] = now
        if st[0] < 1.0:
            st[2] += 1
            return False, 0
        st[0] -= 1.0
        missed, st[2] = st[2], 0
        return True, missed

    # ------------------------------------------------------------------
    # refcount flushing
    # ------------------------------------------------------------------

    def _ref_flush_loop(self):
        from ray_tpu.utils.config import get_config

        period = get_config().ref_heartbeat_interval_s
        last_beat = time.monotonic()
        while not self._closed:
            # event-driven: block until ref activity or the heartbeat is
            # due (an empty update keeps the client-liveness heartbeat
            # alive — actor lifetimes hang off it)
            remain = period - (time.monotonic() - last_beat)
            if self._refs.wait_pending(max(remain, 0.05)):
                time.sleep(self._ref_interval)   # coalesce into one RPC
            if self._closed:
                return
            now = time.monotonic()
            beat = now - last_beat >= period
            if self._ref_flush_now(force_heartbeat=beat) or beat:
                last_beat = now
            if beat:
                self._sweep_promote_pending()

    def _sweep_promote_pending(self):
        """Drop promotion-on-arrival promises whose objects became
        cluster-visible some other way (large returns land in the
        executing node's shm + location directory, never through the
        direct-return path) — without this sweep a long-lived driver
        passing pending refs into tasks grows the set without bound."""
        with self._mem_cv:
            candidates = list(self._promote_pending)
        if not candidates:
            return
        visible = [o for o in candidates
                   if self.store.contains(bytes.fromhex(o))]
        remote = [o for o in candidates if o not in set(visible)]
        if remote:
            try:
                locs = self._gcs.call("get_object_locations", oids=remote)
                visible += [o for o, nodes in locs.items() if nodes]
            except Exception:  # noqa: BLE001 - GCS busy: next beat
                pass
        if visible:
            with self._mem_cv:
                self._promote_pending.difference_update(visible)

    def _ref_flush_now(self, force_heartbeat: bool = False) -> bool:
        """Send pending ref deltas (serialized by a lock so the loop and
        synchronous borrower flushes never interleave a payload). The
        protocol round itself is refcount.flush_once, shared with the
        worker loop; this wrapper adds the driver-only lineage cleanup."""
        if not self._ref_enabled or self._closed:
            return False
        from ray_tpu.runtime.refcount import flush_once

        def call(method, **kwargs):
            if kwargs.get("remove"):
                # dropped refs lose reconstructability too (the object
                # is gone; resurrecting it would leak)
                with self._lineage_lock:
                    for oid_hex in kwargs["remove"]:
                        self._lineage.pop(oid_hex, None)
            return self._gcs.call(method, **kwargs)

        with self._ref_send_lock:
            return flush_once(self._refs, call, self.client_id, "driver",
                              force_heartbeat)

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------

    def put(self, value) -> ObjectRef:
        """Small values land in the owner's in-process MEMORY store
        (reference: small ``ray.put`` objects live in the owner's
        CoreWorkerMemoryStore, memory_store.h:43) — zero store/raylet
        RPCs; the serialize/arrival hooks promote to shm the moment the
        ref travels off-process. Large values seal into shm with a held
        read ref; the pin registration is BATCHED (one raylet RPC per
        flush — same protocol as the worker's task-return reports), the
        seal-hold keeping the object eviction-safe until the pin lands."""
        oid = ObjectID.from_random()
        if self._use_memstore:
            payload, obj, caught = object_codec.encode_bytes(
                value, limit=self._memstore_put_limit)
            if payload is not None:
                oid_hex = oid.hex()
                self._memstore[oid_hex] = payload
                if caught:
                    # the put value contains ObjectRefs: contains-edges
                    # anchor on the outer oid (same as direct returns)
                    self._refs.add_contains(oid_hex, caught)
                self._note_owned(oid_hex, len(payload))
                return ObjectRef(oid)
            # too large for the memory tier: reuse the serialized form
            size = object_codec.put_value_durable(
                self.store, oid.binary(), value, hold=True,
                preserialized=obj, contained=caught,
                request_space=lambda n: self._raylet.call(
                    "request_space", nbytes=n))
        else:
            size = object_codec.put_value_durable(
                self.store, oid.binary(), value, hold=True,
                request_space=lambda n: self._raylet.call("request_space",
                                                          nbytes=n))
        if size > 0:
            with self._put_report_cv:
                self._put_report_buf.append((oid.hex(), size))
                self._put_report_cv.notify()
        self._note_owned(oid.hex(), size)
        return ObjectRef(oid)

    def _note_owned(self, oid_hex: str, size: int,
                    callsite: str | None = None):
        """Owner-side accounting for an object this process created
        (memory plane). Active only while the process has a ref drain —
        same gate ObjectRef tracking uses."""
        if not _refcount.is_active():
            return
        if callsite is None and self._mem_callsite:
            # inlined capture: one call frame instead of two on the
            # hot path (fenced by memory_accounting_overhead_ratio)
            self._refs.note_owned_here(oid_hex, size)
            return
        self._refs.note_owned(oid_hex, size, callsite)

    def _evict_mem_objects(self, oids: list):
        """Refcount release hook: every local ref to these oids died —
        drop the in-process copies (the authoritative release of any
        PROMOTED shm copy rides the normal ref protocol)."""
        pop = self._memstore.pop
        for oid_hex in oids:
            pop(oid_hex, None)

    def _promote_mem_object(self, oid_hex: str):
        """Serialize hook: an ObjectRef is being pickled (task arg, put
        payload, client channel...). If its object lives only in this
        process's memory store, write it through to the shm store + pin
        report NOW — the serialized ref may travel to a process that can
        only resolve cluster-visible objects. Runs before the enclosing
        dumps() returns, so promotion always precedes the send. A ref
        serialized BEFORE its direct return arrived is marked for
        promotion-on-arrival instead (the object exists nowhere yet;
        when the push reply lands it must go cluster-visible, not just
        into this process's memory)."""
        if self._closed:
            return
        with self._mem_cv:
            payload = self._memstore.get(oid_hex)
            if payload is None:
                # not here yet: if it's not already cluster-visible,
                # promote when (if ever) it arrives as a direct return
                if not self.store.contains(bytes.fromhex(oid_hex)):
                    self._promote_pending.add(oid_hex)
                return
        from ray_tpu._private.shm_store import (ObjectExistsError,
                                                StoreFullError)

        try:
            object_codec.put_raw(self.store, bytes.fromhex(oid_hex),
                                 payload, hold=True)
        except ObjectExistsError:
            return  # already cluster-visible
        except StoreFullError:
            try:
                self._raylet.call("request_space", nbytes=len(payload))
                object_codec.put_raw(self.store, bytes.fromhex(oid_hex),
                                     payload, hold=True)
            except Exception:  # noqa: BLE001 - keep the mem copy; a
                return        # remote consumer degrades to ObjectLost
        with self._put_report_cv:
            self._put_report_buf.append((oid_hex, len(payload)))
            self._put_report_cv.notify()

    def _accept_direct_results(self, results: dict):
        """Small task returns that rode the push reply (reference: the
        owner's in-process memory store for direct-call returns,
        memory_store.h:43): land each in the process-local memory store
        — no shm write, no pin RPC, no location tracking. Falls back to
        the durable shm path when ref counting is off (nothing would
        ever evict the memory copies)."""
        if self._use_memstore and not self._closed:
            if _refcount.is_active():
                for oid_hex, payload in results.items():
                    self._refs.note_owned_size(oid_hex, len(payload))
            with self._mem_cv:
                self._memstore.update(results)
                self._mem_arrivals += 1
                promote = ([o for o in results
                            if o in self._promote_pending]
                           if self._promote_pending else ())
                self._promote_pending.difference_update(promote)
                self._mem_cv.notify_all()
            for oid_hex in promote:
                self._promote_mem_object(oid_hex)
            # every local ref may have died while a result was in flight
            # (submit-and-forget chains): the release hook already fired
            # for those oids, so no death notice will ever come again —
            # any copy kept now leaks the memstore forever. Applies to
            # EVERY arriving oid, not just promote-pending ones (a
            # promoted shm copy serves any remote consumer).
            dead = [o for o in results if self._refs.count(o) == 0]
            if dead:
                with self._mem_cv:
                    for oid_hex in dead:
                        self._memstore.pop(oid_hex, None)
            return
        from ray_tpu._private.shm_store import (ObjectExistsError,
                                                StoreFullError)

        for oid_hex, payload in results.items():
            if self._closed:
                return
            oid = bytes.fromhex(oid_hex)
            placed = False
            exists = False
            for _ in range(20):
                try:
                    object_codec.put_raw(self.store, oid, payload,
                                         hold=True)
                    placed = True
                    break
                except ObjectExistsError:
                    # a racing duplicate execution already landed this
                    # result (first write won, its own report carries
                    # the pin): neither report nor park — parking would
                    # livelock the flusher on a permanent Exists
                    exists = True
                    break
                except StoreFullError:
                    try:
                        self._raylet.call("request_space",
                                          nbytes=len(payload))
                    except Exception:  # noqa: BLE001
                        pass
                    time.sleep(0.02)
            if placed:
                if _refcount.is_active():
                    self._refs.note_owned_size(oid_hex, len(payload))
                with self._put_report_cv:
                    self._put_report_buf.append((oid_hex, len(payload)))
                    self._put_report_cv.notify()
            elif not exists:
                # NEVER silently drop the only copy of a result: park it
                # for the put-report flusher to retry once space frees
                # (blocking this lease pusher thread longer would stall
                # its task pushes instead)
                with self._put_report_cv:
                    self._direct_retry.append((oid_hex, payload))
                    self._put_report_cv.notify()

    def _put_report_loop(self):
        """Drain put reports into batched report_objects RPCs, releasing
        each object's seal-hold once its pin is confirmed. Also retries
        parked direct results that hit a full store."""
        while not self._closed:
            retry = None
            with self._put_report_cv:
                while (not self._put_report_buf and not self._direct_retry
                       and not self._closed):
                    self._put_report_cv.wait(timeout=0.5)
                if self._direct_retry:
                    retry, self._direct_retry = self._direct_retry, []
            if self._closed:
                return
            if retry:
                self._accept_direct_results(dict(retry))
                if self._closed:
                    return
            if self._put_report_buf:
                from ray_tpu.utils.config import get_config as _gc

                time.sleep(_gc().put_report_linger_s)   # coalesce burst
            with self._put_report_cv:
                batch, self._put_report_buf = self._put_report_buf, []
            if not batch:
                continue
            # One idempotency token per logical batch, held across
            # retries: a reply lost AFTER the raylet applied the pins
            # (healed partition, transient reset) makes the retry a
            # server-side no-op instead of a double-apply. The seal-holds
            # are what keep these objects alive until their pins land:
            # retry rather than releasing unpinned sole copies into LRU
            # eviction.
            import uuid as _uuid
            token = _uuid.uuid4().hex
            sent = False
            while not self._closed:
                try:
                    self._raylet.call("report_objects", entries=batch,
                                      token=token)
                    sent = True
                    break
                except Exception:  # noqa: BLE001 - raylet unreachable
                    time.sleep(0.05)
            if not sent:
                continue
            if self._closed:
                continue   # store may be unmapped: never touch
            for oid_hex, _ in batch:
                try:
                    self.store.release(bytes.fromhex(oid_hex))
                except Exception:  # noqa: BLE001
                    pass

    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        # FAST PATH: every object already local — in the process memory
        # store (direct small returns: zero syscalls) or sealed in shm
        # (local puts) — resolves through dict hits + ONE batched store
        # call instead of contains + get + release C round trips per
        # object (reference analog: the owner's in-process memory store
        # hit, memory_store.h:43). No size cap: the store's get_many /
        # release_many chunk internally (shm_store.BATCH_WINDOW), so the
        # process-shared mutex hold stays bounded per C call even for a
        # 200k-ref envelope get.
        mem = self._memstore if self._use_memstore else None
        bins = [r.id.binary() for r in refs]
        if bins is not None:
            payloads = [mem.get(r.hex()) for r in refs] if mem \
                else [None] * len(refs)
            misses = [b for b, p in zip(bins, payloads) if p is None]
            views = self.store.get_many(misses) if misses else []
            if all(v is not None for v in views):
                epoch0 = (self._refs.created_epoch()
                          if self._ref_enabled else 0)
                out = []
                err = None
                it = iter(views)
                try:
                    for p in payloads:
                        v = memoryview(p) if p is not None else next(it)
                        value, is_error = object_codec.decode_view(v)
                        if is_error:
                            err = value
                            break
                        out.append(value)
                finally:
                    del views, it
                    if misses:
                        self.store.release_many(misses)
                if err is not None:
                    raise err
                if self._ref_enabled and \
                        self._refs.created_epoch() != epoch0:
                    self._ref_flush_now()
                return out
            # drop the partial hits' read refs; the slow path re-reads
            # per object as each becomes local
            hits = [b for b, v in zip(misses, views) if v is not None]
            del views
            if hits:
                self.store.release_many(hits)
        oids = [r.id.hex() for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = [o for o in oids if not (mem and o in mem)
                   and not self.store.contains(bytes.fromhex(o))]
        recover_tick = 0.0
        mem_skips = 0
        while pending:
            # Local completions (direct small returns, same-host tasks)
            # resolve with a cheap contains scan — only a WINDOW of the
            # truly-unresolved set goes to the raylet per cycle.
            # Shipping the full pending list (200k oids = multi-MB
            # frames + full-set wave loops server-side) melted large
            # gets; _read_local re-pulls per object anyway, so the
            # window is a locality warmer, not a correctness gate.
            # Re-filter BEFORE the deadline check: a final ensure_local
            # that localized everything while eating the budget must
            # exit success, not GetTimeoutError.
            pending = [o for o in pending if not (mem and o in mem)
                       and not self.store.contains(bytes.fromhex(o))]
            if not pending:
                break
            if deadline is not None and deadline - time.monotonic() <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {len(pending)} objects")
            # Two arrival planes, two waits. Direct returns land in the
            # process MEMORY store, which the raylet cannot observe —
            # parking inside the raylet while they piled up locally ate
            # its whole timeout. So: wait briefly on the direct-arrival
            # cv; only when that plane is quiet (no notify AND no
            # arrival since), park in the raylet's ensure_local, which
            # wakes event-driven on local shm seals and triggers remote
            # pulls. A direct result landing mid-park costs at most the
            # 0.25 s park timeout.
            if self._use_memstore:
                with self._mem_cv:
                    arrivals0 = self._mem_arrivals
                    woke = self._mem_cv.wait(timeout=0.02)
                # BOUNDED skip: a sustained direct-result stream wakes
                # this cv every cycle, and skipping ensure_local on
                # every wake would starve the remote-pull path forever
                # (a shm-only object on another node never gets its pull
                # issued). At most ~5 consecutive wakes (~100 ms) defer
                # the ensure_local window.
                if (woke or self._mem_arrivals != arrivals0) \
                        and mem_skips < 5:
                    mem_skips += 1
                    continue
            mem_skips = 0
            # short park only when the direct-arrival blind spot exists
            # (memstore on): without it the raylet's event-driven wait
            # covers every arrival path, and 0.25s parks would 8x the
            # blocked-get RPC churn for nothing
            step = 0.25 if self._use_memstore else 2.0
            if deadline is not None:
                step = min(step, max(deadline - time.monotonic(), 0.01))
            window = pending[:4096]
            leftover = self._raylet.call("ensure_local", oids=window,
                                         timeout_s=step)
            now = time.monotonic()
            if leftover and now - recover_tick >= 2.0:
                recover_tick = now
                self._recover_lost(leftover)
        out = []
        epoch0 = self._refs.created_epoch() if self._ref_enabled else 0
        for oid_hex in oids:
            out.append(self._read_local(oid_hex, deadline))
        if self._ref_enabled and self._refs.created_epoch() != epoch0:
            # the values carried nested ObjectRefs (this process just
            # became a borrower): register the holds synchronously so
            # the owner dropping the outer cannot free the inners first
            self._ref_flush_now()
        return out

    # ------------------------------------------------------------------
    # lineage reconstruction
    # ------------------------------------------------------------------

    def _recover_lost(self, oids: list[str], depth: int = 0):
        """Re-run creating tasks from lineage (reference:
        ObjectRecoveryManager::RecoverObject object_recovery_manager.h:90
        → TaskManager::ResubmitTask) for two loss modes:

        1. Tombstoned objects: the GCS once knew them and every location
           died with its node — deterministic loss, budgeted by
           max_retries.
        2. Presumed-lost pending tasks: output never registered anywhere
           and the submission is older than the pending grace (the task
           was queued/running on a node that died — no object existed to
           tombstone). Heuristic: a merely slow task gets a DUPLICATE
           submission (harmless via first-write-wins), capped by its own
           small budget that does NOT consume the max_retries lineage
           budget."""
        uniq = list(set(oids))
        lost = self._gcs.call("get_lost_objects", oids=uniq)
        # LEGACY-path tasks lost IN FLIGHT leave no tombstone (their output
        # never existed): a pending object with lineage, no location
        # anywhere, and a stale submission is presumed dead-with-its-node
        # and resubmitted (idempotent: first-write-wins). Lease-path tasks
        # never enter this heuristic — their owner observes the lease
        # connection break synchronously and retries/fails on the spot.
        lost_set = set(lost)
        unlocated = [o for o, locs in self._gcs.call(
            "get_object_locations", oids=uniq).items()
            if not locs and o not in lost_set]
        now = time.monotonic()
        for oid_hex in unlocated:
            with self._lineage_lock:
                entry = self._lineage.get(oid_hex)
            if entry is None:
                continue
            # eligible: legacy-path tasks (no lease watches them), or
            # lease-path tasks that COMPLETED (their object existed; the
            # node died before the batched location flush — nothing is
            # watching anymore). A lease-path task still running is
            # watched by its lease connection: never resubmit on time.
            if not (entry.get("legacy") or entry["task"].get("_completed")):
                continue
            ref_t = max(entry.get("submitted_at", 0.0),
                        entry.get("last_resubmit", 0.0))
            if now - ref_t <= self._pending_grace_s:
                continue
            if entry.get("pending_resubmits", 0) >= 3:
                # duplicate budget spent: keep WAITING (the original or a
                # duplicate may still be running — raising here would
                # fail healthy long tasks). Callers bound the wait with
                # get(timeout=...); max_retries=0 tasks have no lineage
                # entry at all, so in-flight loss there also surfaces as
                # a timeout (the reference detects that case through its
                # worker-lease channel, which this design doesn't have).
                continue
            with self._lineage_lock:
                entry["pending_resubmits"] = 1 + entry.get(
                    "pending_resubmits", 0)
            self._reconstruct(oid_hex, depth, pending_grace=True)
        for oid_hex in lost:
            if self.store.contains(bytes.fromhex(oid_hex)):
                continue
            with self._lineage_lock:
                entry = self._lineage.get(oid_hex)
                reconstructing = oid_hex in self._reconstructing
            if entry is None:
                raise exc.ObjectLostError(
                    oid_hex,
                    "all copies lost with their node and no lineage is "
                    "available to reconstruct it (max_retries=0?)")
            if (entry["attempts"] <= 0 and not reconstructing
                    and time.monotonic() - entry.get("last_resubmit", 0.0)
                    > self._lineage_grace_s):
                raise exc.ObjectLostError(
                    oid_hex, "lineage re-execution budget exhausted")
            self._reconstruct(oid_hex, depth)

    def _reconstruct(self, oid_hex: str, depth: int = 0,
                     pending_grace: bool = False):
        if depth > 10:
            return
        with self._lineage_lock:
            entry = self._lineage.get(oid_hex)
            if entry is None:
                return
            if not pending_grace and entry["attempts"] <= 0:
                return
            if oid_hex in self._reconstructing:
                return
            # a re-execution is likely still running — don't stack another
            # (the tombstone only clears when the new copy registers).
            # Known limit: a re-run longer than the grace gets a duplicate
            # submission; first-write-wins keeps that harmless. The
            # pending-task path uses its own (shorter) grace, already
            # checked by the caller against submit/resubmit time.
            grace = (self._pending_grace_s if pending_grace
                     else self._lineage_grace_s)
            if (time.monotonic() - entry.get("last_resubmit", 0.0)
                    < grace):
                return
            if not pending_grace:
                # only DETERMINISTIC loss consumes the max_retries budget;
                # heuristic pending resubmits have their own cap
                entry["attempts"] -= 1
            entry["last_resubmit"] = time.monotonic()
            self._reconstructing.add(oid_hex)
        try:
            # deps first: a re-run will fail on lost inputs (recursive
            # lineage re-execution, depth-bounded)
            deps = entry["deps"]
            if deps:
                dep_lost = self._gcs.call("get_lost_objects", oids=deps)
                for dep in dep_lost:
                    if not self.store.contains(bytes.fromhex(dep)):
                        self._reconstruct(dep, depth + 1)
            # first-write-wins makes a duplicate re-execution harmless.
            # Strip the completion marker: the COPY is a fresh attempt,
            # and a stale _completed=True would disable the lease-break
            # retry/fail path for it.
            resubmit = dict(entry["task"])
            resubmit.pop("_completed", None)
            self._leases.submit(resubmit)
        finally:
            with self._lineage_lock:
                self._reconstructing.discard(oid_hex)

    def _read_local(self, oid_hex: str, deadline):
        """Read a locally-available object; if it was evicted between the
        ensure_local and the read (LRU pressure), re-pull and retry."""
        from ray_tpu._private.shm_store import ObjectNotFoundError

        if self._use_memstore:
            payload = self._memstore.get(oid_hex)
            if payload is not None:
                value, is_error = object_codec.decode_view(
                    memoryview(payload))
                if is_error:
                    raise value
                return value
        for _ in range(3):
            try:
                value, is_error = object_codec.get_value(
                    self.store, bytes.fromhex(oid_hex), timeout_ms=0)
            except ObjectNotFoundError:
                step = 5.0
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        raise exc.GetTimeoutError(
                            f"object {oid_hex[:8]} evicted and re-pull "
                            f"timed out") from None
                    step = min(step, remain)
                self._raylet.call("ensure_local", oids=[oid_hex],
                                  timeout_s=step)
                continue
            if is_error:
                raise value
            return value
        raise exc.ObjectLostError(oid_hex, "evicted repeatedly under "
                                  "store memory pressure")

    def wait(self, refs, num_returns=1, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list = []
        not_ready = list(refs)
        mem = self._memstore if self._use_memstore else None
        while True:
            still = []
            for r in not_ready:
                if (mem and r.id.hex() in mem) or \
                        self.store.contains(r.id.binary()):
                    ready.append(r)
                else:
                    still.append(r)
            not_ready = still
            if len(ready) >= num_returns or not not_ready:
                break
            # check remote locations for objects created elsewhere
            oids = [r.id.hex() for r in not_ready]
            locs = self._gcs.call("get_object_locations", oids=oids)
            for r in list(not_ready):
                if locs.get(r.id.hex()):
                    ready.append(r)
                    not_ready.remove(r)
            if len(ready) >= num_returns or not not_ready:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        return ready, not_ready

    def free(self, refs: list):
        """Release object memory cluster-wide AND drop lineage, so the
        objects cannot be reconstructed (reference: ray.internal.free)."""
        oids = [r.id.hex() for r in refs]
        with self._lineage_lock:
            for o in oids:
                self._lineage.pop(o, None)
        for o in oids:
            self._memstore.pop(o, None)
        try:
            self._raylet.call("free_objects", oids=oids)
        except (OSError, ConnectionLost):
            pass

    def cancel(self, ref: ObjectRef, force: bool = False):
        """Best-effort task cancellation (reference ``ray.cancel``):
        queued tasks are dequeued, running tasks interrupted (``force``:
        worker killed); consumers of the return object observe
        ``TaskCancelledError``. Finished tasks are untouched."""
        # lease-managed tasks are invisible to the raylet queues — the
        # owner cancels them itself
        hit = self._leases.cancel({ref.id.hex()}, force=force)
        if hit is not None:
            state, task = hit
            if state == "queued":
                self._seal_cancel_error(task)
            return
        try:
            self._raylet.call("cancel_task", oids=[ref.id.hex()],
                              force=force)
        except (OSError, ConnectionLost):
            pass

    def _seal_cancel_error(self, task: dict):
        self._fail_task_returns(task, exc.TaskCancelledError(
            f"task {task.get('name', '?')} cancelled while queued"))

    def note_return_owner(self, spec: TaskSpec):
        pass  # ownership is tracked centrally (GCS object directory)

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------

    _EMPTY_ARGS_BLOB = cloudpickle.dumps(([], {}), protocol=5)

    def _wire_args(self, spec: TaskSpec, pin_sink: set | None = None):
        """Replace top-level ObjectRefs with markers (reference semantics:
        only top-level args are resolved before execution). Plain-data
        args take the C pickler (~5x the Python-level cloudpickle
        Pickler on small payloads — the per-call cost that matters at
        10k+ submits/s); closures/lambdas in args fall back to
        cloudpickle.

        ``pin_sink``: collects the oid of every ref the task depends on
        (top-level markers AND refs nested inside arg containers, found
        via serialization capture) so the submitter can pin them for the
        task's lifetime (reference: submitted-task references,
        reference_count.h:61)."""
        if not spec.args and not spec.kwargs:
            return self._EMPTY_ARGS_BLOB
        args = [("__objref__", a.hex()) if isinstance(a, ObjectRef) else a
                for a in spec.args]
        kwargs = {k: ("__objref__", v.hex()) if isinstance(v, ObjectRef)
                  else v for k, v in spec.kwargs.items()}
        if self._use_memstore:
            # top-level ref args never hit ObjectRef.__reduce__ (markers
            # replace them before pickling), so the serialize-hook
            # promotion doesn't fire — promote memory-store residents
            # (or mark not-yet-arrived results promote-on-arrival)
            # here: the executing worker resolves args from the
            # cluster-visible store
            for a in spec.args:
                if isinstance(a, ObjectRef):
                    self._promote_mem_object(a.hex())
            for v in spec.kwargs.values():
                if isinstance(v, ObjectRef):
                    self._promote_mem_object(v.hex())
        if pin_sink is not None:
            pin_sink.update(a[1] for a in args
                            if type(a) is tuple and len(a) == 2
                            and a[0] == "__objref__")
            pin_sink.update(v[1] for v in kwargs.values()
                            if type(v) is tuple and len(v) == 2
                            and v[0] == "__objref__")
        # The C pickler fast path is gated to builtin SCALARS only:
        # stdlib pickle serializes __main__-defined classes by REFERENCE
        # (workers can't resolve them — their __main__ is worker_main),
        # and a container could hide one; cloudpickle pickles by value.
        if all(type(a) in _SCALAR_TYPES
               or (type(a) is tuple and len(a) == 2 and a[0] == "__objref__")
               for a in args) and all(
                   type(v) in _SCALAR_TYPES for v in kwargs.values()):
            import pickle
            return pickle.dumps((args, kwargs), protocol=5)
        # nested refs inside containers surface through the capture hook
        with self._refs.capture() as cap:
            blob = cloudpickle.dumps((args, kwargs), protocol=5)
        if pin_sink is not None:
            pin_sink.update(cap.oids)
        return blob

    def _function_blob(self, fn):
        """Pickle-once, EXPORT-once function table (reference:
        ``_private/function_manager.py:228`` — each function is exported
        to the GCS once; executors fetch by id and cache). Tasks then
        carry only the 16-byte content id: at 10k+ submits/s, shipping
        the ~500-byte closure blob per task (and hashing it per task on
        the worker) was a measurable slice of the frame encode/decode.

        Returns ``(fn_id, closure_oids)`` — ObjectRefs captured in the
        function's CLOSURE are task dependencies too: every submit pins
        them alongside the args (the cache keeps the captured set, so
        repeat submits pin without re-pickling)."""
        key = id(fn)
        hit = self._fn_blobs.get(key)
        if hit is not None and hit[0] is fn:
            return hit[1], hit[2]
        import hashlib

        with self._refs.capture() as cap:
            blob = cloudpickle.dumps(fn, protocol=5)
        closure_oids = frozenset(cap.oids)
        fn_id = hashlib.blake2b(blob, digest_size=16).hexdigest()
        # registration must land BEFORE any task referencing the id is
        # pushed (synchronous; once per function per driver). Content-
        # addressed: re-registering the same id is an idempotent no-op.
        self._gcs.call("kv_put", ns="__functions__", key=fn_id, value=blob)
        from ray_tpu.utils.config import get_config as _gc

        if len(self._fn_blobs) > _gc().fn_export_cache_size:
            self._fn_blobs.clear()
        # fn ref pins id(fn) stable
        self._fn_blobs[key] = (fn, fn_id, closure_oids)
        return fn_id, closure_oids

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        streaming = spec.num_returns in ("streaming", "dynamic")
        if streaming:
            # end-of-stream count object = the declared return id: lease
            # breaks / worker deaths seal their error exactly where the
            # consumer's end check reads (runtime/streaming.py). Streams
            # are not retried (a partially consumed stream is not
            # idempotently re-runnable), so no lineage entry either.
            from ray_tpu.runtime.streaming import (ObjectRefGenerator,
                                                   stream_end_ref)
            spec.return_ids = [stream_end_ref(spec.task_id.binary()).id]
            spec.max_retries = 0
        else:
            spec.return_ids = [ObjectID.from_random()
                               for _ in range(spec.num_returns)]
        # The caller's ObjectRefs MUST exist before the task can reach a
        # pusher thread: _accept_direct_results reads count==0 as "every
        # ref died while the result was in flight" and drops the arriving
        # copy. A worker fast enough to reply before this thread got back
        # to construct the refs (routinely ~0.03% of a 10k-task drain on
        # a loaded host) would lose the only copy of the result, and the
        # later get() waits forever.
        out_refs = ([] if streaming
                    else [ObjectRef(oid) for oid in spec.return_ids])
        if out_refs and _refcount.is_active():
            # this process OWNS the submitted task's returns: one
            # callsite capture per submit, shared across the return ids
            # (sizes backfill when the results report in)
            cs = (_refcount.capture_callsite()
                  if self._mem_callsite else None)
            for oid in spec.return_ids:
                self._refs.note_owned(oid.hex(), 0, cs)
        if spec.task_type == TaskType.ACTOR_TASK:
            self._submit_actor_task(spec)
        else:
            pin_oids: set = set()
            fn_id, closure_oids = self._function_blob(spec.function)
            pin_oids.update(closure_oids)
            task = {
                "task_id": spec.task_id.hex(),
                "name": spec.function_name,
                "function_id": fn_id,
                "args_blob": self._wire_args(spec, pin_oids),
                "return_oids": [o.hex() for o in spec.return_ids],
                "resources": dict(spec.resources.resources),
                "strategy": _wire_strategy(spec),
                "max_retries": spec.max_retries,
                "runtime_env": spec.runtime_env,
                "trace_ctx": spec.trace_ctx,
                "namespace": self._effective_namespace(),
            }
            if streaming:
                task["streaming"] = True
            if pin_oids and self._ref_enabled:
                # pin the args for the task's lifetime; the executing
                # worker releases after it finishes ("pinned" tells it
                # a pin exists to release)
                task["pinned"] = True
                self._refs.add_task_pins(spec.task_id.hex(),
                                         sorted(pin_oids))
            if spec.max_retries > 0:
                deps = [a.id.hex() for a in spec.args
                        if isinstance(a, ObjectRef)]
                deps += [v.id.hex() for v in spec.kwargs.values()
                         if isinstance(v, ObjectRef)]
                entry = {"task": task, "deps": deps,
                         "attempts": spec.max_retries,
                         "submitted_at": time.monotonic()}
                with self._lineage_lock:
                    for oid in spec.return_ids:
                        self._lineage[oid.hex()] = entry
                    # bounded (reference: RAY_max_lineage_bytes caps the
                    # lineage the owner pins): oldest entries dropped —
                    # their objects simply lose reconstructability
                    while len(self._lineage) > self._lineage_max:
                        self._lineage.pop(next(iter(self._lineage)))
            self._leases.submit(task)
        if streaming:
            from ray_tpu.runtime.streaming import ObjectRefGenerator
            return [ObjectRefGenerator(spec.task_id.binary())]
        return out_refs

    def _legacy_submit(self, task: dict):
        """Raylet-queue submission (placement-constrained tasks, lease
        fallbacks). These have no lease channel watching them, so their
        lineage entries opt back into the pending-grace loss heuristic."""
        with self._lineage_lock:
            for oid_hex in task.get("return_oids", ()):
                entry = self._lineage.get(oid_hex)
                if entry is not None:
                    entry["legacy"] = True
        self._raylet.call("submit_task", task=task)

    def _fail_task_returns(self, task: dict, error: BaseException):
        """A lease broke under a non-retriable task: seal error objects so
        waiters unblock (reference: TaskManager failing the task spec's
        returns). Skips oids that were completed before the break."""
        locs: dict = {}
        try:
            locs = self._gcs.call("get_object_locations",
                                  oids=list(task.get("return_oids", ())))
        except Exception:  # noqa: BLE001 - degrade to local checks
            pass
        err = (error if isinstance(error, exc.RayTpuError)
               else exc.WorkerCrashedError(
                   f"worker lease broke while executing "
                   f"{task.get('name', '?')}: {error}"))
        if task.get("pinned"):
            # the task will never run to release its arg pins itself
            self._refs.release_task_pin(task.get("task_id", ""))
        with self._mem_cv:
            # no result will ever arrive for these: drop any promised
            # promotion-on-arrival (the error object sealed below is
            # cluster-visible on its own)
            self._promote_pending.difference_update(
                task.get("return_oids", ()))
        for oid_hex in task.get("return_oids", ()):
            if locs.get(oid_hex):
                continue  # the task actually finished before the break
            oid = bytes.fromhex(oid_hex)
            if self._closed:
                return  # store may be unmapped mid-shutdown: never touch
            if oid_hex in self._memstore or self.store.contains(oid):
                continue
            try:
                size = object_codec.put_value_durable(
                    self.store, oid, err, is_error=True, hold=True,
                    request_space=lambda n: self._raylet.call(
                        "request_space", nbytes=n))
                try:
                    self._raylet.call("report_object", oid=oid_hex,
                                      size=size)
                finally:
                    if size > 0:
                        self.store.release(oid)
            except Exception:  # noqa: BLE001 - racing completion wins
                pass

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def _effective_namespace(self, override: str | None = None) -> str:
        if override:
            return override
        from ray_tpu.runtime_context import current_task_namespace

        return current_task_namespace() or self.namespace

    def create_actor(self, spec: TaskSpec, name: str | None = None,
                     namespace: str | None = None,
                     lifetime: str | None = None) -> ActorID:
        actor_id = ActorID.from_random()
        spec.actor_id = actor_id
        ns = self._effective_namespace(namespace)
        pin_oids: set = set()
        with self._refs.capture() as _cls_cap:
            cls_blob = cloudpickle.dumps(spec.function, protocol=5)
        pin_oids.update(_cls_cap.oids)
        creation = {
            "task_id": spec.task_id.hex(),
            "name": spec.function_name,
            "function_blob": cls_blob,
            "args_blob": self._wire_args(spec, pin_oids),
            "return_oids": [ObjectID.from_random().hex()],
            "resources": dict(spec.resources.resources),
            "max_concurrency": spec.max_concurrency,
            "runtime_env": spec.runtime_env,
            "namespace": ns,
        }
        if pin_oids and self._ref_enabled:
            creation["pinned"] = True
            self._refs.add_task_pins(spec.task_id.hex(), sorted(pin_oids))
            # the pin must exist at the GCS before the raylet-hosted
            # creation task can finish and release it
            self._ref_flush_now()
        strategy = _wire_strategy(spec)
        entry = {
            "kwargs": {
                "actor_id": actor_id.hex(), "name": name,
                "creation_spec": creation,
                "resources": dict(spec.resources.resources),
                "max_restarts": spec.max_restarts,
                "pg_id": strategy.get("pg_id"),
                "namespace": ns,
                "owner_id": self.client_id if self._ref_enabled else None,
                "lifetime": lifetime,
            },
            # named registrations stay synchronous: the name-conflict
            # ValueError must surface from THIS call, not a later one
            "ev": threading.Event() if name is not None else None,
            "error": None,
        }
        # subscribe BEFORE the registration can produce events, so the
        # alive push is never lost to the subscribe race
        self._ensure_actor_sub()
        with self._reg_cv:
            while (len(self._reg_pending) >= self._reg_window
                   and not self._closed):
                self._reg_cv.wait(timeout=0.1)
            self._reg_outbox.append(entry)
            self._reg_pending.add(actor_id.hex())
            self._reg_cv.notify_all()
        self._ensure_reg_flusher()
        if entry["ev"] is not None:
            entry["ev"].wait(timeout=60.0)
            if entry["error"] is not None:
                raise ValueError(entry["error"])
        return actor_id

    # -- registration coalescer ----------------------------------------

    def _ensure_reg_flusher(self):
        if self._reg_flusher_started:
            return
        with self._reg_cv:
            if self._reg_flusher_started:
                return
            self._reg_flusher_started = True
        threading.Thread(target=self._reg_flush_loop, daemon=True,
                         name="actor-register-flusher").start()

    def _reg_flush_loop(self):
        while not self._closed:
            with self._reg_cv:
                while not self._reg_outbox and not self._closed:
                    self._reg_cv.wait(timeout=0.2)
                if self._closed:
                    batch = self._reg_outbox
                    self._reg_outbox = []
                else:
                    batch = None
            if batch is not None:   # shutdown: fail the stragglers
                self._reg_fail_batch(batch, "runtime shut down")
                return
            if self._reg_linger_s > 0:
                time.sleep(self._reg_linger_s)   # coalesce the burst
            with self._reg_cv:
                batch = self._reg_outbox[:self._reg_batch_cap]
                self._reg_outbox = self._reg_outbox[self._reg_batch_cap:]
            if not batch:
                continue
            try:
                reply = self._gcs.call(
                    "register_actors",
                    actors=[e["kwargs"] for e in batch])
                results = reply["results"]
            except Exception as e:  # noqa: BLE001 - redial window burned
                self._reg_fail_batch(batch, repr(e))
                continue
            with self._reg_cv:
                for entry, res in zip(batch, results):
                    aid = entry["kwargs"]["actor_id"]
                    self._reg_pending.discard(aid)
                    if not res.get("ok"):
                        err = res.get("error", "registration failed")
                        entry["error"] = err
                        self._reg_failed[aid] = err
                    if entry["ev"] is not None:
                        entry["ev"].set()
                self._reg_cv.notify_all()

    def _reg_fail_batch(self, batch: list, err: str):
        with self._reg_cv:
            for entry in batch:
                aid = entry["kwargs"]["actor_id"]
                self._reg_pending.discard(aid)
                entry["error"] = err
                self._reg_failed[aid] = err
                if entry["ev"] is not None:
                    entry["ev"].set()
            self._reg_cv.notify_all()

    def _reg_drain(self, actor_id_hex: str, timeout: float = 10.0):
        """Block until this actor's registration frame has been acked
        (ordering guard for kill/lookup racing the coalescer)."""
        deadline = time.monotonic() + timeout
        with self._reg_cv:
            while (actor_id_hex in self._reg_pending
                   and time.monotonic() < deadline and not self._closed):
                self._reg_cv.wait(timeout=0.1)

    # -- pushed actor-location table (CH_ACTOR subscription) -----------

    def _ensure_actor_sub(self) -> bool:
        if not self._actor_pubsub or self._closed:
            return False
        if self._actor_sub is not None:
            return True
        with self._actor_sub_lock:
            if self._actor_sub is None and not self._closed:
                from ray_tpu.runtime.rpc import PushSubscriber

                self._actor_sub = PushSubscriber(
                    self.gcs_address,
                    {"method": "subscribe", "channels": ["actor"]},
                    self._on_actor_event,
                    reconnect=True,   # survive a GCS restart
                    label="driver")
        return True

    def _on_actor_event(self, msg: dict):
        events = msg.get("batch") or (msg,)
        with self._actor_table_cv:
            for ev in events:
                aid = ev.get("actor_id")
                kind = ev.get("event")
                if aid is None or kind is None:
                    continue
                if kind == "alive":
                    self._actor_table[aid] = {
                        "state": "ALIVE",
                        "address": ev.get("address"),
                        "push_addr": ev.get("push_addr"),
                        "num_restarts": ev.get("num_restarts", 0)}
                elif kind == "restarting":
                    self._actor_table[aid] = {"state": "RESTARTING"}
                    self._actor_locations.pop(aid, None)
                elif kind == "dead":
                    self._actor_table[aid] = {
                        "state": "DEAD",
                        "death_reason": ev.get("reason", "dead")}
                    self._actor_locations.pop(aid, None)
            self._actor_table_cv.notify_all()

    def _install_location(self, actor_id_hex: str, addr, num_restarts):
        entry = (tuple(addr), num_restarts)
        with self._seq_lock:
            if self._actor_seq_inc.get(actor_id_hex) != entry[1]:
                self._actor_seq[actor_id_hex] = 0
                self._actor_seq_inc[actor_id_hex] = entry[1]
            self._actor_locations[actor_id_hex] = entry
        return entry

    def _actor_location(self, actor_id_hex: str,
                        timeout: float | None = None):
        """(address, incarnation) of an ALIVE actor — the DIRECT worker
        push port when the actor has one (reference:
        DirectActorTaskSubmitter dials the actor process, no raylet hop),
        else its raylet. Caches, and resets the caller-side sequence
        numbering when a new incarnation is observed (restarted actors
        start their ordering from 0).

        The cache-HIT path stays bare (>10k calls/s on the direct-call
        path); a MISS is traced + registered with the stuck-call
        watchdog, so a resolve wedged on a dead pushed table shows up
        in ``util.state.stuck_calls`` with its parent span."""
        cached = self._actor_locations.get(actor_id_hex)
        if cached is not None:
            return cached
        token = _tracing.call_started("actor_resolve", actor_id_hex[:16])
        try:
            with _tracing.span(f"resolve:{actor_id_hex[:8]}",
                               kind="control"):
                return self._actor_location_miss(actor_id_hex, timeout)
        finally:
            _tracing.call_finished(token)

    def _actor_location_miss(self, actor_id_hex: str,
                             timeout: float | None = None):
        """Slow path of :meth:`_actor_location`.

        Steady state is pubsub-driven: waits on the CH_ACTOR pushed
        table; a counted get_actor poll fires only after a quiet
        ``actor_resolve_fallback_s`` window (events published before the
        subscription landed, or lost across a redial)."""
        cached = self._actor_locations.get(actor_id_hex)
        if cached is not None:
            return cached
        # only the MISS path is timed: the cache hit above runs at
        # >10k calls/s on the direct-call path and must stay bare
        from ray_tpu.util import metrics as _metrics
        t_resolve = time.perf_counter() if _metrics.enabled() else 0.0
        if timeout is None:
            timeout = self._resolve_timeout_s
        deadline = time.monotonic() + timeout
        use_push = self._ensure_actor_sub()
        poll_at = (time.monotonic() + self._resolve_fallback_s
                   if use_push else time.monotonic())
        while True:
            if use_push:
                with self._actor_table_cv:
                    ent = self._actor_table.get(actor_id_hex)
                if ent is not None:
                    if ent["state"] == "ALIVE":
                        addr = ent.get("push_addr") or ent.get("address")
                        if addr is not None:
                            if t_resolve:
                                self._h_actor_resolve.observe(
                                    time.perf_counter() - t_resolve)
                            return self._install_location(
                                actor_id_hex, addr,
                                ent.get("num_restarts", 0))
                    elif ent["state"] == "DEAD":
                        raise exc.ActorDiedError(
                            actor_id_hex,
                            ent.get("death_reason", "dead"))
                err = self._reg_failed.get(actor_id_hex)
                if err is not None:
                    raise exc.ActorDiedError(actor_id_hex, err)
            now = time.monotonic()
            if now >= deadline:
                break
            if now >= poll_at:
                # fallback poll — the regression test asserts this
                # counter stays flat once the pushed table is warm
                self._actor_get_polls += 1
                info = self._gcs.call("get_actor", actor_id=actor_id_hex)
                if info is None:
                    if actor_id_hex in self._reg_pending:
                        # still queued in the coalescer: not an error
                        poll_at = now + self._resolve_fallback_s
                        continue
                    raise exc.ActorDiedError(actor_id_hex,
                                             "unknown actor")
                if info["state"] == "ALIVE":
                    addr = info.get("push_addr") or info["address"]
                    if t_resolve:
                        self._h_actor_resolve.observe(
                            time.perf_counter() - t_resolve)
                    return self._install_location(
                        actor_id_hex, addr, info.get("num_restarts", 0))
                if info["state"] == "DEAD":
                    raise exc.ActorDiedError(
                        actor_id_hex, info.get("death_reason", "dead"))
                poll_at = now + (self._resolve_fallback_s if use_push
                                 else 0.02)
                if not use_push:
                    time.sleep(0.02)
                continue
            if use_push:
                with self._actor_table_cv:
                    self._actor_table_cv.wait(
                        timeout=min(0.2, deadline - now, poll_at - now))
        raise exc.ActorUnavailableError(
            f"actor {actor_id_hex[:8]} not ALIVE within {timeout}s")

    @property
    def ACTOR_WINDOW(self):
        """Max unacked tasks per actor (outbox + in flight); flag
        ``actor_submit_window`` — deep enough to absorb enqueue-ack
        latency without stalling the submitter."""
        return self._actor_window

    def _submit_actor_task(self, spec: TaskSpec):
        """Enqueue one actor call for the flusher (seq assigned HERE so
        caller submission order = sequence order; the worker's per-caller
        seq buffer tolerates wire reordering). Blocks only when the
        actor's unacked window is full."""
        actor_hex = spec.actor_id.hex()
        pin_oids: set = set()
        task = {
            "task_id": spec.task_id.hex(),
            "name": spec.function_name,
            "actor_id": actor_hex,
            "method_name": spec.actor_method_name,
            "args_blob": self._wire_args(spec, pin_oids),
            "return_oids": [o.hex() for o in spec.return_ids],
            "caller_id": self.caller_id,
            "trace_ctx": spec.trace_ctx,
        }
        if pin_oids and self._ref_enabled:
            task["pinned"] = True
            self._refs.add_task_pins(spec.task_id.hex(), sorted(pin_oids))
        if spec.num_returns in ("streaming", "dynamic"):
            # generator METHOD: worker-side _store_returns streams the
            # yields exactly like a generator task
            task["streaming"] = True
        try:
            addr, incarnation = self._actor_location(actor_hex)
        except (exc.ActorDiedError, exc.ActorUnavailableError, OSError,
                ConnectionLost, LookupError) as e:
            self._resend_actor_task(task, actor_hex, e, None)
            return
        with self._seq_lock:
            seq = self._actor_seq.get(actor_hex, 0)
            self._actor_seq[actor_hex] = seq + 1
        task["seq"] = seq
        task["incarnation"] = incarnation
        with self._outbox_cv:
            while (self._actor_unacked.get(actor_hex, 0)
                   >= self.ACTOR_WINDOW and not self._closed):
                self._outbox_cv.wait(timeout=0.1)
            self._actor_outbox.setdefault(actor_hex, []).append(
                (task, tuple(addr)))
            self._actor_unacked[actor_hex] = \
                self._actor_unacked.get(actor_hex, 0) + 1
            # watchdog: one entry per unacked actor call, finished by
            # _ack_actor_tasks in the same FIFO order acks arrive
            self._wd_tokens.setdefault(actor_hex, deque()).append(
                _tracing.call_started(
                    "actor_call",
                    f"{spec.actor_method_name} ({actor_hex[:8]})"))
            self._outbox_cv.notify_all()
        self._ensure_actor_reaper()

    def _actor_client(self, addr) -> RpcClient:
        """Cached per-address client: a fresh socket + reader thread per
        actor CALL is ruinous on polling paths (report buses poll at
        50 Hz)."""
        addr = tuple(addr)
        with self._actor_clients_lock:
            client = self._actor_clients.get(addr)
            if client is not None and not client._closed:
                return client
        # connect OUTSIDE the lock: one unreachable raylet (30s connect
        # timeout) must not stall submissions to every other node
        fresh = RpcClient(addr, label="owner")
        evicted = None
        with self._actor_clients_lock:
            client = self._actor_clients.get(addr)
            if client is not None and not client._closed:
                fresh.close()  # lost the race; reuse the winner
                return client
            self._actor_clients[addr] = fresh
            # bounded: with direct actor push, keys are per-worker ports
            # (one per actor incarnation) — a driver churning actors
            # would otherwise leak a dead client per retired actor.
            # ONLY closed entries are evicted below the hard cap:
            # closing a LIVE client drops its in-flight submit frames,
            # and at >cap live actors that cascades into an eviction/
            # resend storm that stalls the whole submission plane (the
            # 2k-actor envelope ran minutes-per-round-trip until this).
            # The hard cap is a leak backstop sized far above any sane
            # live-actor count per driver; sockets + parked reader
            # threads are cheap, lost replies are not.
            if len(self._actor_clients) > self._actor_client_soft_cap:
                for k, c in list(self._actor_clients.items()):
                    if c._closed and k != addr:
                        evicted = self._actor_clients.pop(k)
                        break
                else:
                    if len(self._actor_clients) > \
                            self._actor_client_cap:
                        oldest = next(iter(self._actor_clients))
                        if oldest != addr:
                            evicted = self._actor_clients.pop(oldest)
        if evicted is not None:
            try:
                evicted.close()
            except Exception:  # noqa: BLE001
                pass
        return fresh

    def _drop_actor_client(self, addr):
        with self._actor_clients_lock:
            client = self._actor_clients.pop(tuple(addr), None)
        if client is not None:
            client.close()

    def _flush_actor_outbox(self):
        """Flusher duty: pack each actor's queued submissions into
        submit_actor_tasks batch frames (split on address change so a
        mid-burst relocation never mixes destinations)."""
        with self._outbox_cv:
            if not self._actor_outbox:
                return
            snapshot = self._actor_outbox
            self._actor_outbox = {}
        for actor_hex, items in snapshot.items():
            window = self._actor_windows.setdefault(actor_hex, deque())
            i = 0
            while i < len(items):
                addr = items[i][1]
                batch = []
                while i < len(items) and items[i][1] == addr:
                    batch.append(items[i][0])
                    i += 1
                try:
                    client = self._actor_client(addr)
                    if len(batch) == 1:
                        pending = client.call_async("submit_actor_task",
                                                    task=batch[0])
                    else:
                        pending = client.call_async("submit_actor_tasks",
                                                    tasks=batch)
                except (exc.ActorDiedError, exc.ActorUnavailableError,
                        OSError, ConnectionLost, LookupError) as e:
                    for t in batch:
                        self._resend_actor_task(t, actor_hex, e, addr)
                    self._ack_actor_tasks(actor_hex, len(batch))
                    continue
                window.append((batch, pending, addr, time.monotonic()))

    def _ack_actor_tasks(self, actor_hex: str, n: int):
        with self._outbox_cv:
            left = self._actor_unacked.get(actor_hex, 0) - n
            if left > 0:
                self._actor_unacked[actor_hex] = left
            else:
                self._actor_unacked.pop(actor_hex, None)
            tokens = self._wd_tokens.get(actor_hex)
            done = []
            if tokens:
                for _ in range(min(n, len(tokens))):
                    done.append(tokens.popleft())
                if not tokens:
                    self._wd_tokens.pop(actor_hex, None)
            self._outbox_cv.notify_all()
        for t in done:
            _tracing.call_finished(t)

    def _drain_actor_window(self, actor_hex: str):
        """Flusher duty: pop completed batch frames off the window head;
        on failure, resend the failed batch AND everything after it in
        order (they shared the dead socket / stale incarnation). Never
        blocks on an unready head — a stalled frame is failed only past
        its 60s deadline so one wedged actor cannot stall the flusher."""
        window = self._actor_windows.get(actor_hex)
        while window:
            tasks, pending, addr, sent_at = window[0]
            if not pending._ev_reply[0].is_set():
                if time.monotonic() - sent_at < 60.0:
                    return
                err: BaseException = TimeoutError(
                    f"actor submit unacked for 60s ({actor_hex[:8]})")
            else:
                err = None
                try:
                    pending.result(timeout=0)
                except (exc.ActorDiedError, exc.ActorUnavailableError,
                        OSError, ConnectionLost, TimeoutError,
                        LookupError) as e:
                    err = e
            window.popleft()
            self._ack_actor_tasks(actor_hex, len(tasks))
            if err is None:
                self._record_acked_tasks(actor_hex, tasks)
            if err is not None:
                failed = [(t, addr) for t in tasks]
                while window:
                    ts, _, a, _ = window.popleft()
                    failed += [(t, a) for t in ts]
                    self._ack_actor_tasks(actor_hex, len(ts))
                for t, a in failed:
                    self._resend_actor_task(t, actor_hex, err, a)
                return

    def _resend_actor_task(self, task: dict, actor_hex: str,
                           first_err: BaseException, addr_used):
        """Retry with a refreshed location under a bounded redial window
        (reference: client resend protocol on actor restart). A single
        shot here condemned LIVE actors during transient partitions of
        the owner link: the retry dial failed inside the same cut and
        the task came back ActorDiedError even though the actor process
        never died. Transport errors now drop the cached client, back
        off (config ``rpc_backoff_*``), and redial until
        ``rpc_redial_window_s`` closes; an ActorDiedError /
        ActorUnavailableError from the GCS is authoritative and stops
        the loop at once. Seq handling: same incarnation keeps the
        ORIGINAL seq (the actor never consumed it; duplicates dedup
        worker-side), a new incarnation renumbers from the reset counter
        — either way no gap stalls the actor's ordered queue."""
        if self._closed:
            return  # store may be unmapped mid-shutdown: never touch
        if isinstance(first_err, (OSError, ConnectionLost)) \
                and addr_used is not None:
            # transport failure ON THE RAYLET LINK: reconnect on retry.
            # App-level errors keep the healthy shared connection.
            try:
                self._drop_actor_client(addr_used)
            except Exception:  # noqa: BLE001
                pass
        self._actor_locations.pop(actor_hex, None)
        from ray_tpu.utils.config import get_config as _gc
        cfg = _gc()
        deadline = time.monotonic() + cfg.rpc_redial_window_s
        attempt = 0
        err: BaseException = first_err
        while True:
            attempt += 1
            addr = None
            try:
                addr, incarnation = self._actor_location(actor_hex)
                if incarnation != task.get("incarnation"):
                    with self._seq_lock:
                        seq = self._actor_seq.get(actor_hex, 0)
                        self._actor_seq[actor_hex] = seq + 1
                    task["seq"] = seq
                    task["incarnation"] = incarnation
                client = self._actor_client(addr)
                client.call("submit_actor_task", task=task, timeout=30)
                self._record_acked_tasks(actor_hex, (task,))
                return
            except (exc.ActorDiedError, exc.ActorUnavailableError) as e:
                err = e      # GCS verdict: no amount of redialing helps
                break
            except (OSError, ConnectionLost, LookupError,
                    TimeoutError) as e:
                err = e
                if addr is not None:
                    try:
                        self._drop_actor_client(addr)
                    except Exception:  # noqa: BLE001
                        pass
                self._actor_locations.pop(actor_hex, None)
                import random as _random
                delay = min(cfg.rpc_backoff_max_s,
                            cfg.rpc_backoff_initial_s
                            * cfg.rpc_backoff_multiplier ** (attempt - 1))
                if cfg.rpc_backoff_jitter:
                    delay *= 1.0 + cfg.rpc_backoff_jitter * (
                        2.0 * _random.random() - 1.0)
                if time.monotonic() + delay >= deadline or self._closed:
                    break
                time.sleep(delay)
        err = err if isinstance(err, exc.RayTpuError) else \
            exc.ActorDiedError(actor_hex, repr(err),
                               restart_count=task.get("incarnation", 0))
        if task.get("pinned"):
            self._refs.release_task_pin(task.get("task_id", ""))
        for oid_hex in task.get("return_oids", ()):
            oid = bytes.fromhex(oid_hex)
            if not self.store.contains(oid):
                try:
                    object_codec.put_value(self.store, oid, err,
                                           is_error=True)
                except Exception:  # noqa: BLE001
                    pass
        # The consumed seq would leave a GAP the actor's ordered queue
        # waits on forever (stalling every later call). Queue a noop
        # gap-filler: the reaper keeps sending it until it lands or the
        # actor moves to a new incarnation (which resets numbering).
        # (No "seq" in the task means the failure hit BEFORE numbering —
        # nothing was consumed, no gap exists.)
        if not task.get("noop") and "seq" in task:
            filler = {"actor_id": actor_hex, "caller_id": self.caller_id,
                      "task_id": task.get("task_id", ""),
                      "method_name": "", "args_blob": b"",
                      "return_oids": [], "noop": True,
                      "seq": task["seq"],
                      "incarnation": task.get("incarnation", 0)}
            with self._seq_lock:
                self._actor_gap_fillers.setdefault(actor_hex,
                                                   []).append(filler)
            self._ensure_actor_reaper()

    def _flush_gap_fillers(self):
        """Reaper duty: deliver queued seq gap-fillers; drop them once
        the actor reached a new incarnation (fresh numbering, no gap)."""
        with self._seq_lock:
            items = [(a, list(fs)) for a, fs in
                     self._actor_gap_fillers.items() if fs]
        for actor_hex, fillers in items:
            for filler in fillers:
                delivered = False
                try:
                    addr, incarnation = self._actor_location(actor_hex)
                    if incarnation != filler["incarnation"]:
                        delivered = True   # numbering reset: gap is moot
                    else:
                        self._actor_client(addr).call(
                            "submit_actor_task", task=filler, timeout=10)
                        delivered = True
                except (exc.ActorDiedError, exc.ActorUnavailableError):
                    delivered = True       # actor gone: nobody waits
                except Exception:  # noqa: BLE001 - retry next tick
                    pass
                if delivered:
                    with self._seq_lock:
                        fs = self._actor_gap_fillers.get(actor_hex, [])
                        if filler in fs:
                            fs.remove(filler)

    def _record_acked_tasks(self, actor_hex: str, tasks):
        """Track acked-but-unresolved calls for the dead-actor sweep.
        Once the worker acks a submit, the submit plane (window +
        resend) is done with the task — but its return oids are only as
        durable as the worker's queue. Entries leave via the sweep:
        pruned when their oids land, failed typed when the actor dies."""
        with self._inflight_lock:
            per = self._actor_inflight.setdefault(actor_hex, {})
            for t in tasks:
                if t.get("noop") or not t.get("return_oids"):
                    continue
                per[t["task_id"]] = (tuple(t["return_oids"]),
                                     t.get("incarnation", 0),
                                     bool(t.get("pinned")))
            if not per:
                self._actor_inflight.pop(actor_hex, None)

    def _sweep_dead_actor_calls(self):
        """Reaper duty: fail calls that died INSIDE a dead actor's
        queue. A crash-killed worker takes its accepted-but-unfinished
        queue down with it; nothing on the submit plane retries those
        (they were acked), so without this sweep their return oids are
        never written and an untimed get() wedges forever. The pushed
        actor table (CH_ACTOR) is the authority: state DEAD — or an
        ALIVE entry whose incarnation has advanced past the one that
        accepted the call — means the accepting queue is gone, and the
        unresolved oids get a typed ActorDiedError."""
        with self._inflight_lock:
            snapshot = [(a, dict(per))
                        for a, per in self._actor_inflight.items()]
        for actor_hex, per in snapshot:
            resolved = [tid for tid, (oids, _, _p) in per.items()
                        if all(self.store.contains(bytes.fromhex(o))
                               for o in oids)]
            if resolved:
                with self._inflight_lock:
                    live = self._actor_inflight.get(actor_hex)
                    if live:
                        for tid in resolved:
                            live.pop(tid, None)
                            per.pop(tid, None)
                        if not live:
                            self._actor_inflight.pop(actor_hex, None)
            if not per:
                continue
            with self._actor_table_cv:
                ent = self._actor_table.get(actor_hex)
            reg_err = self._reg_failed.get(actor_hex)
            if ent is None and reg_err is None:
                continue
            if reg_err is not None:
                dead = {tid: exc.ActorDiedError(actor_hex, reg_err)
                        for tid in per}
            elif ent["state"] == "DEAD":
                restarts = ent.get("num_restarts", 0)
                dead = {tid: exc.ActorDiedError(
                            actor_hex, ent.get("death_reason", "dead"),
                            restart_count=restarts)
                        for tid in per}
            else:
                # ALIVE but restarted: calls acked into an OLDER
                # incarnation died with it (the fresh process has an
                # empty queue and will never see them)
                restarts = ent.get("num_restarts", 0)
                dead = {tid: exc.ActorDiedError(
                            actor_hex,
                            f"actor restarted; incarnation {inc} died "
                            f"holding this call",
                            restart_count=restarts)
                        for tid, (_, inc, _p) in per.items()
                        if inc < restarts}
            if not dead:
                continue
            for tid, err in dead.items():
                oids, _inc, pinned = per[tid]
                for oid_hex in oids:
                    oid = bytes.fromhex(oid_hex)
                    if not self.store.contains(oid):
                        try:
                            object_codec.put_value(self.store, oid, err,
                                                   is_error=True)
                        except Exception:  # noqa: BLE001
                            pass
                if pinned:
                    try:
                        self._refs.release_task_pin(tid)
                    except Exception:  # noqa: BLE001
                        pass
            with self._inflight_lock:
                live = self._actor_inflight.get(actor_hex)
                if live:
                    for tid in dead:
                        live.pop(tid, None)
                    if not live:
                        self._actor_inflight.pop(actor_hex, None)

    def _ensure_actor_reaper(self):
        """Start the actor submit flusher: the single thread that sends
        outbox batches, drains reply windows (surfacing failures of the
        LAST submits in a burst even when no further call touches the
        actor), and delivers seq gap-fillers."""
        if self._actor_reaper_started:
            return
        with self._seq_lock:
            if self._actor_reaper_started:
                return
            self._actor_reaper_started = True

        def loop():
            gap_tick = 0.0
            sweep_tick = 0.0
            while not self._closed:
                linger = False
                with self._outbox_cv:
                    if not self._actor_outbox:
                        # frames in flight need a tight drain cadence (acks
                        # feed the flow-control window); fully idle can
                        # sleep longer — a new submit notifies the cv
                        busy = any(self._actor_windows.values())
                        self._outbox_cv.wait(timeout=0.002 if busy else 0.05)
                    else:
                        linger = any(self._actor_windows.values())
                if linger:
                    # mid-burst micro-linger: when the flusher keeps pace
                    # with the submitter, batches collapse to size 1 and
                    # throughput falls back to per-call framing. 200us of
                    # accumulation is hidden behind the frame already in
                    # flight; isolated single calls (no in-flight frames)
                    # skip it entirely.
                    time.sleep(0.0002)
                try:
                    self._flush_actor_outbox()
                except Exception:  # noqa: BLE001
                    pass
                for actor_hex in list(self._actor_windows):
                    try:
                        self._drain_actor_window(actor_hex)
                    except Exception:  # noqa: BLE001
                        pass
                now = time.monotonic()
                if now - gap_tick >= 0.05:
                    gap_tick = now
                    try:
                        self._flush_gap_fillers()
                    except Exception:  # noqa: BLE001
                        pass
                if now - sweep_tick >= 0.5:
                    sweep_tick = now
                    try:
                        self._sweep_dead_actor_calls()
                    except Exception:  # noqa: BLE001
                        pass

        threading.Thread(target=loop, daemon=True,
                         name="actor-submit-flusher").start()

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        # a kill racing the registration coalescer would find no actor
        # at the GCS and silently no-op — drain this id's frame first
        self._reg_drain(actor_id.hex())
        self._gcs.call("kill_actor", actor_id=actor_id.hex(),
                       no_restart=no_restart)
        entry = self._actor_locations.pop(actor_id.hex(), None)
        if entry is not None:
            # retire the dead incarnation's cached push-port client
            self._drop_actor_client(entry[0])

    def get_actor(self, name: str, namespace: str | None = None) -> ActorID:
        info = self._gcs.call("get_actor", name=name,
                              namespace=self._effective_namespace(namespace))
        if info is None:
            raise ValueError(f"Failed to look up actor with name {name!r}")
        return ActorID.from_hex(info["actor_id"])

    def actor_state(self, actor_id: ActorID):
        return None  # class name not tracked cluster-side (handle shows id)

    # ------------------------------------------------------------------
    # cluster info / lifecycle
    # ------------------------------------------------------------------

    def cluster_resources(self) -> dict:
        return self._gcs.call("cluster_resources")["total"]

    def available_resources_snapshot(self) -> dict:
        return self._gcs.call("cluster_resources")["available"]

    def shutdown(self):
        if self._owns_flusher:
            # clean exit = immediate owner-death semantics: the GCS
            # drops this client's holds and reaps its non-detached
            # actors (reference: driver exit, gcs_actor_manager.cc:632)
            try:
                self._gcs.call("unregister_client",
                               client_id=self.client_id)
            except Exception:  # noqa: BLE001 - timeout reaping covers it
                pass
            from ray_tpu.runtime import refcount as _refcount
            _refcount.release_flusher(self.client_id)
            self._refs.reset()
        if self._use_memstore:
            # reset() clears hooks wholesale for the flusher owner; a
            # nested runtime must unhook only its own
            self._refs.remove_release_hook(self._memstore_release_hook)
            self._refs.remove_serialize_hook(self._memstore_serialize_hook)
            self._memstore.clear()
        self._closed = True
        try:
            from ray_tpu.runtime import metrics_plane as _mp
            _mp.set_annex_provider(self._mem_annex_key, None)
        except Exception:  # noqa: BLE001 - best-effort plane teardown
            pass
        try:
            self._metrics_pusher.stop()
        except Exception:  # noqa: BLE001 - best-effort plane teardown
            pass
        with self._reg_cv:
            self._reg_cv.notify_all()   # reg flusher drains + exits
        if self._log_sub is not None:
            self._log_sub.close()
        if self._actor_sub is not None:
            try:
                self._actor_sub.close()
            except Exception:  # noqa: BLE001
                pass
        self._leases.stop()
        # grace for pusher threads already past their _closed checks to
        # finish touching the store before it unmaps
        time.sleep(0.05)
        with self._actor_clients_lock:
            clients = list(self._actor_clients.values())
            self._actor_clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        try:
            self._gcs.close()
            self._raylet.close()
            self.store.close()
        except Exception:  # noqa: BLE001
            pass


def _wire_strategy(spec: TaskSpec) -> dict:
    s = spec.scheduling_strategy
    out = {"kind": s.kind}
    if s.node_id is not None:
        out["node_id"] = s.node_id if isinstance(s.node_id, str) \
            else s.node_id.hex()
    if s.placement_group_id is not None:
        out["pg_id"] = s.placement_group_id.hex()
        out["bundle_index"] = s.bundle_index
    return out
