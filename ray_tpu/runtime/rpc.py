"""Minimal threaded RPC over TCP: length-prefixed pickled messages.

Reference analog: ``src/ray/rpc/`` (async gRPC server/client templates).
Wire format: 8-byte big-endian length + pickled payload. Two interaction
shapes, mirroring the reference's usage:

- request/response: ``RpcClient.call(method, **kwargs)`` — blocking, safe
  from many threads (per-call matching via request ids).
- server push: a connection can be promoted to a push channel (pubsub long
  poll analog, ``src/ray/pubsub/``) — the server holds it and writes
  messages; the client runs a reader thread delivering to a callback.

All services in the cluster plane (GCS, raylet) are ``RpcServer`` subclasses
exposing ``rpc_<method>`` handlers; handlers run on a thread per connection.
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from typing import Any, Callable

from ray_tpu.runtime import fault_injection as _fi
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

_LEN = struct.Struct(">Q")

# RPC-boundary stage timer (metrics plane): server-side handler latency
# per method. Handles are cached per method name so the hot dispatch
# path pays one dict hit + one bisect, never a tag merge.
_rpc_hist: _metrics.Histogram | None = None
_rpc_handles: dict[str, _metrics._HistHandle] = {}


def _rpc_handle(method: str) -> _metrics._HistHandle:
    global _rpc_hist
    h = _rpc_handles.get(method)
    if h is None:
        if _rpc_hist is None:
            _rpc_hist = _metrics.histogram(
                "ray_tpu_rpc_server_s",
                "server-side RPC handler latency by method",
                tag_keys=("method",))
        h = _rpc_handles[method] = _rpc_hist.handle({"method": method})
    return h


class ConnectionLost(Exception):
    pass


# Cross-language frames: a payload starting with b"M" is msgpack (the
# C++ client's wire — see runtime/xlang.py); pickled payloads start with
# the PROTO opcode 0x80, so the marker never collides. Servers answer
# each request in the format it arrived in.
_MSGPACK_MARK = 0x4D  # "M"


def send_msg(sock: socket.socket, obj: Any,
             lock: threading.Lock | None = None, fmt: str = "pickle"):
    if fmt == "msgpack":
        from ray_tpu.runtime import xlang

        if isinstance(obj, dict) and isinstance(obj.get("error"),
                                                BaseException):
            # exceptions don't cross the language boundary as objects
            obj = {**obj, "error": repr(obj["error"])}
        data = bytes((_MSGPACK_MARK,)) + xlang.dumps(obj)
    else:
        data = pickle.dumps(obj, protocol=5)
    frame = _LEN.pack(len(data)) + data
    if lock:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def recv_msg(sock: socket.socket) -> Any:
    return recv_msg_any(sock)[0]


def recv_msg_any(sock: socket.socket) -> tuple[Any, str]:
    """Receive one frame, returning (message, format)."""
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    payload = _recv_exact(sock, length)
    if payload and payload[0] == _MSGPACK_MARK:
        from ray_tpu.runtime import xlang

        return xlang.loads(payload[1:]), "msgpack"
    return pickle.loads(payload), "pickle"


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionLost("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class RpcServer:
    """Threaded TCP server; dispatches ``{"method": m, ...}`` requests to
    ``self.rpc_<m>(conn, **payload)``. A handler may return
    ``HELD`` to take ownership of the connection (push channels)."""

    HELD = object()

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(256)
        self.address = self._sock.getsockname()
        # endpoint label for the fault-injection plane (subclasses set a
        # role name: "gcs", "raylet", "worker")
        self.fault_label = type(self).__name__
        self._stopping = False
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{type(self).__name__}-accept",
            daemon=True,
        )

    def start(self):
        self._accept_thread.start()
        return self

    def stop(self):
        self._stopping = True
        # Wake the accept thread and JOIN it BEFORE closing the listener:
        # close() frees the fd NUMBER for the kernel to reuse, and a
        # thread still parked in (or retrying) accept() on that number
        # would accept on whatever socket inherits it — observed stealing
        # a freshly-bound server's connections in back-to-back test
        # clusters and closing them (spurious ConnectionLost on clients
        # of the NEW server). shutdown() makes the parked accept return
        # EINVAL deterministically.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass
        # sever live connections: a stopped server must not keep accepting
        # work over held sockets — peers would get "ok" replies for
        # requests that silently black-hole (e.g. a task enqueued on a
        # raylet whose dispatch loop is gone)
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stopping:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._stopping:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def release_conn(self, conn: socket.socket):
        """Drop a HELD connection from the severing set once its owner is
        done with it (held-handler finally blocks / publisher dead-sub
        cleanup). Prevents dead sockets accumulating in _conns."""
        with self._conns_lock:
            self._conns.discard(conn)

    def _serve_conn(self, conn: socket.socket):
        send_lock = threading.Lock()
        held = False
        fmt = "pickle"
        try:
            while not self._stopping:
                try:
                    req, fmt = recv_msg_any(conn)
                except (ConnectionLost, OSError, EOFError):
                    return
                if self._stopping:
                    # request raced the shutdown: error instead of
                    # half-processing on a dying service
                    try:
                        send_msg(conn, {"_id": req.get("_id"),
                                        "error": ConnectionLost(
                                            "server stopping")}, send_lock,
                                 fmt=fmt)
                    except (OSError, Exception):  # noqa: BLE001
                        pass
                    return
                req_id = req.pop("_id", None)
                method = req.pop("method")
                # trace header: present only when the caller was inside
                # a span — untraced traffic (heartbeats, metric pushes)
                # carries no header and produces no server spans
                wire_trace = req.pop("_trace", None)
                deliveries = 1
                if _fi.plane.active:
                    try:
                        peer = conn.getpeername()
                    except OSError:
                        peer = ("?", 0)
                    action = _fi.plane.consult(self.fault_label, "recv",
                                               peer, method)
                    if action == _fi.DROP:
                        continue   # request lost before dispatch
                    if action == _fi.RESET:
                        return     # finally: discard + on_disconnect
                    if action == _fi.DUPLICATE:
                        deliveries = 2
                for delivery in range(deliveries):
                    # an injected duplicate re-dispatches from a fresh
                    # deserialization — handlers may mutate their payload
                    payload = (req if delivery == deliveries - 1
                               else pickle.loads(pickle.dumps(req)))
                    outcome = self._dispatch_one(conn, send_lock, fmt,
                                                 method, req_id, payload,
                                                 wire_trace)
                    if outcome == "held":
                        held = True
                        return
                    if outcome == "gone":
                        return
        finally:
            if not held:
                with self._conns_lock:
                    self._conns.discard(conn)
            if not self._stopping:
                self.on_disconnect(conn)

    def _invoke(self, handler, method, conn, send_lock, payload):
        if _metrics.enabled():
            t0 = time.perf_counter()
            result = handler(conn, send_lock, **payload)
            _rpc_handle(method).observe(time.perf_counter() - t0)
            return result
        return handler(conn, send_lock, **payload)

    def _dispatch_one(self, conn, send_lock, fmt, method, req_id,
                      payload, wire_trace=None) -> str:
        """Dispatch one request and send its reply. Returns "ok", "held"
        (handler took the connection), or "gone" (peer unreachable)."""
        handler = getattr(self, f"rpc_{method}", None)
        try:
            if handler is None:
                raise AttributeError(f"no rpc method {method!r}")
            if wire_trace is not None:
                # restore the caller's ambient context so handler-side
                # spans (and any RPCs the handler makes) parent across
                # the hop — the server half of context propagation
                with _tracing.server_span(method, wire_trace):
                    result = self._invoke(handler, method, conn,
                                          send_lock, payload)
            else:
                result = self._invoke(handler, method, conn, send_lock,
                                      payload)
        except BaseException as e:  # noqa: BLE001 - ship to caller
            try:
                self._send_reply(conn, {"_id": req_id, "error": e},
                                 send_lock, fmt, method)
            except OSError:
                return "gone"  # peer gone; nothing to reply to
            except Exception:  # unpicklable exception payload
                try:
                    self._send_reply(conn,
                                     {"_id": req_id,
                                      "error": RuntimeError(repr(e))},
                                     send_lock, fmt, method)
                except OSError:
                    return "gone"
            return "ok"
        if result is RpcServer.HELD:
            # handler owns the connection; it STAYS in _conns so
            # stop() can sever it — the owner calls release_conn
            # when the channel is truly finished
            return "held"
        try:
            self._send_reply(conn, {"_id": req_id, "result": result},
                             send_lock, fmt, method)
        except OSError:
            return "gone"  # peer closed mid-reply (e.g. returned lease)
        except Exception as e:  # noqa: BLE001 - unencodable result
            try:
                self._send_reply(conn, {"_id": req_id,
                                        "error": RuntimeError(repr(e))},
                                 send_lock, fmt, method)
            except OSError:
                return "gone"
        return "ok"

    def _send_reply(self, conn, obj, send_lock, fmt, method):
        if _fi.plane.active:
            try:
                peer = conn.getpeername()
            except OSError:
                peer = ("?", 0)
            action = _fi.plane.consult(self.fault_label, "send", peer,
                                       method)
            if action == _fi.DROP:
                return   # reply lost in flight (handler still applied)
            if action == _fi.RESET:
                raise _fi.InjectedConnectionReset(
                    f"injected reset replying to {method}")
            send_msg(conn, obj, send_lock, fmt=fmt)
            if action == _fi.DUPLICATE:
                send_msg(conn, obj, send_lock, fmt=fmt)
            return
        send_msg(conn, obj, send_lock, fmt=fmt)

    def on_disconnect(self, conn: socket.socket):
        """Override: called when a non-held connection drops."""


class RpcClient:
    """Blocking request/response client, thread-safe, auto-reconnect off."""

    def __init__(self, address: tuple[str, int], timeout: float | None = None,
                 label: str | None = None):
        self.address = tuple(address)
        self._label = label   # fault-injection endpoint of the channel
        if _fi.plane.active:
            _fi.plane.check_connect(label, self.address)
        self._sock = socket.create_connection(self.address, timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(timeout)
        self._send_lock = threading.Lock()
        self._pending: dict[int, list] = {}  # id -> [event, reply, method]
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._reader_started = False
        self._closed = False

    def _ensure_reader(self):
        # guarded: a cached client is shared across threads, and two
        # racing readers interleaving framed reads corrupt the stream
        with self._pending_lock:
            if self._reader_started:
                return
            self._reader_started = True
        threading.Thread(target=self._read_loop, daemon=True).start()

    def _read_loop(self):
        while not self._closed:
            try:
                msg = recv_msg(self._sock)
            except (ConnectionLost, OSError, EOFError):
                self._fail_pending()
                return
            msg_id = msg.get("_id")
            if _fi.plane.active:
                with self._pending_lock:
                    entry = self._pending.get(msg_id)
                method = entry[2] if entry else None
                action = _fi.plane.consult(self._label, "recv",
                                           self.address, method)
                if action == _fi.DROP:
                    continue   # reply lost in flight; caller times out
                if action == _fi.RESET:
                    self._fail_pending()
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    return
                # a duplicated reply delivery is inert: the pending
                # entry is popped exactly once below
            with self._pending_lock:
                ev_reply = self._pending.pop(msg_id, None)
            if ev_reply is not None:
                _tracing.call_finished(ev_reply[3])
                ev_reply[1] = msg
                ev_reply[0].set()

    def _fail_pending(self):
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
            self._closed = True
        # release the fd NOW: a client whose peer was SIGKILLed sits in
        # per-address caches as a dead entry until eviction, and a
        # fault-churned cluster (chaos soak) leaks one fd per killed
        # peer otherwise
        try:
            self._sock.close()
        except OSError:
            pass
        for ev_reply in pending:
            _tracing.call_finished(ev_reply[3])
            ev_reply[1] = {"error": ConnectionLost(
                f"connection to {self.address} lost")}
            ev_reply[0].set()

    def call(self, method: str, timeout: float | None = None, **kwargs):
        return self.call_async(method, **kwargs).result(timeout=timeout)

    def call_async(self, method: str, **kwargs) -> "PendingCall":
        """Send the request and return a handle; multiple in-flight calls
        pipeline over the one connection (the server processes a
        connection's requests in order, so pipelining hides the caller's
        round-trip latency without reordering)."""
        self._ensure_reader()
        with self._pending_lock:
            # _closed must be re-checked INSIDE the lock: the reader's
            # failure path drains _pending and sets _closed under this
            # lock, and an entry registered after that drain would never
            # be completed (permanent hang for timeout=None callers)
            if self._closed:
                raise ConnectionLost(f"client to {self.address} closed")
            msg_id = self._next_id
            self._next_id += 1
            # 4th slot: stuck-call watchdog token, released wherever the
            # pending entry is popped (reply, failure, or caller timeout)
            ev_reply = [threading.Event(), None, method,
                        _tracing.call_started("rpc", method,
                                              target=self.address)]
            self._pending[msg_id] = ev_reply
        kwargs["method"] = method
        kwargs["_id"] = msg_id
        wire = _tracing.wire_context()
        if wire is not None:
            kwargs["_trace"] = wire
        if _fi.plane.active:
            action = _fi.plane.consult(self._label, "send", self.address,
                                       method)
            if action == _fi.DROP:
                # request lost in the network: the pending entry waits
                # out the caller's timeout, as a real drop would
                return PendingCall(self, method, msg_id, ev_reply)
            if action == _fi.RESET:
                self.close()   # reader wakes and drains pending
                raise ConnectionLost(
                    f"injected reset: {self._label} -> {self.address}")
            send_msg(self._sock, kwargs, self._send_lock)
            if action == _fi.DUPLICATE:
                # same frame (same _id) on the wire twice: the server
                # dispatches both; the client keeps the first reply
                send_msg(self._sock, kwargs, self._send_lock)
            return PendingCall(self, method, msg_id, ev_reply)
        send_msg(self._sock, kwargs, self._send_lock)
        return PendingCall(self, method, msg_id, ev_reply)

    def close(self):
        self._closed = True
        try:
            # shutdown() WAKES a reader thread blocked in recv();
            # close() alone leaves it blocked forever (the classic
            # transient-client thread leak)
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class ReconnectingRpcClient:
    """RpcClient wrapper that redials after connection loss — the client
    side of control-plane fault tolerance (reference: GCS clients retry
    through ``gcs_rpc_client.h`` when the GCS restarts). One transparent
    retry per call after a successful redial; GCS mutations are
    idempotent (registry upserts + idempotency tokens on the
    side-effecting RPCs), so a request that was applied right before the
    connection died is safe to repeat.

    Redials run under a UNIFORM deadline with exponential backoff plus
    jitter and a bounded attempt budget (config ``rpc_redial_*`` /
    ``rpc_backoff_*``): a per-call ``timeout`` caps the redial window
    too, so a caller's deadline covers the whole call including
    reconnects — not a fresh window per attempt."""

    def __init__(self, address: tuple, timeout: float | None = None,
                 redial_window_s: float | None = None,
                 label: str | None = None):
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        self.address = tuple(address)
        self._timeout = timeout
        self._label = label
        self._window = (cfg.rpc_redial_window_s if redial_window_s is None
                        else redial_window_s)
        self._max_redials = cfg.rpc_redial_max_attempts
        self._backoff_init = cfg.rpc_backoff_initial_s
        self._backoff_mult = cfg.rpc_backoff_multiplier
        self._backoff_max = cfg.rpc_backoff_max_s
        self._jitter = cfg.rpc_backoff_jitter
        self._client = RpcClient(self.address, timeout=timeout,
                                 label=label)
        self._dial_lock = threading.Lock()

    @property
    def _closed(self):
        return self._client._closed

    def _backoff(self, attempt: int) -> float:
        """Exponential backoff with jitter: attempt 1 sleeps ~initial,
        doubling (by multiplier) to the cap; jitter desynchronizes a
        thundering herd of clients redialing one restarted server."""
        delay = min(self._backoff_max,
                    self._backoff_init * self._backoff_mult ** (attempt - 1))
        if self._jitter:
            delay *= 1.0 + self._jitter * (2.0 * random.random() - 1.0)
        return max(delay, 0.0)

    def _redial(self, failed: RpcClient,
                deadline: float | None = None) -> bool:
        window_end = time.monotonic() + self._window
        if deadline is not None:
            window_end = min(window_end, deadline)
        with self._dial_lock:
            # compare against the CLIENT THAT FAILED, not _closed: a send
            # error can precede the reader thread marking the client
            # closed, and trusting _closed would "retry" on the same dead
            # socket
            if self._client is not failed and not self._client._closed:
                return True  # another caller already reconnected
            failed.close()
            attempt = 0
            while True:
                attempt += 1
                if self._max_redials and attempt > self._max_redials:
                    return False   # redial budget exhausted
                try:
                    self._client = RpcClient(self.address,
                                             timeout=self._timeout,
                                             label=self._label)
                    return True
                except OSError:
                    delay = self._backoff(attempt)
                    if time.monotonic() + delay >= window_end:
                        return False
                    time.sleep(delay)

    def call(self, method: str, timeout: float | None = None, **kwargs):
        deadline = None if timeout is None else time.monotonic() + timeout
        client = self._client
        try:
            return client.call(method, timeout=timeout, **kwargs)
        except (ConnectionLost, OSError):
            if not self._redial(client, deadline):
                raise
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.001))
            return self._client.call(method, timeout=remaining, **kwargs)

    def call_async(self, method: str, **kwargs):
        client = self._client
        try:
            return client.call_async(method, **kwargs)
        except (ConnectionLost, OSError):
            if not self._redial(client):
                raise
            return self._client.call_async(method, **kwargs)

    def close(self):
        self._client.close()


class PendingCall:
    """Handle for an in-flight pipelined request."""

    __slots__ = ("_client", "_method", "_msg_id", "_ev_reply")

    def __init__(self, client: RpcClient, method: str, msg_id: int,
                 ev_reply: list):
        self._client = client
        self._method = method
        self._msg_id = msg_id
        self._ev_reply = ev_reply

    def result(self, timeout: float | None = None):
        if not self._ev_reply[0].wait(timeout=timeout):
            with self._client._pending_lock:
                popped = self._client._pending.pop(self._msg_id, None)
            if popped is not None:
                _tracing.call_finished(popped[3])
            raise TimeoutError(
                f"rpc {self._method} timed out after {timeout}s")
        reply = self._ev_reply[1]
        if "error" in reply:
            raise reply["error"]
        return reply["result"]


class PushSubscriber:
    """Client side of a server-push channel (pubsub subscribe).

    ``reconnect=True`` redials and re-subscribes after a dropped
    connection (e.g. a GCS restart) — messages published while
    disconnected are lost, matching pubsub semantics."""

    def __init__(self, address: tuple[str, int], subscribe_msg: dict,
                 callback: Callable[[Any], None], *,
                 reconnect: bool = False,
                 reconnect_delay_s: float = 1.0,
                 label: str | None = None):
        self._address = tuple(address)
        self._subscribe_msg = subscribe_msg
        self._callback = callback
        self._reconnect = reconnect
        self._reconnect_delay_s = reconnect_delay_s
        self._label = label
        self._closed = False
        self._sock = self._dial()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _dial(self):
        if _fi.plane.active:
            _fi.plane.check_connect(self._label, self._address)
        sock = socket.create_connection(self._address, timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_msg(sock, self._subscribe_msg)
        return sock

    def _loop(self):
        while not self._closed:
            try:
                msg = recv_msg(self._sock)
                if _fi.plane.active:
                    action = _fi.plane.consult(self._label, "recv",
                                               self._address, None)
                    if action == _fi.DROP:
                        continue   # pushed message lost (pubsub allows)
                    if action == _fi.RESET:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        raise ConnectionLost("injected reset")
            except (ConnectionLost, OSError, EOFError):
                if not self._reconnect or self._closed:
                    return
                time.sleep(self._reconnect_delay_s)
                try:
                    self._sock = self._dial()
                except OSError:
                    continue   # server still down; retry next round
                continue
            try:
                self._callback(msg)
            except Exception:  # noqa: BLE001 - subscriber errors are isolated
                pass

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def wait_for_port(address: tuple[str, int], timeout: float = 10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(tuple(address), timeout=1):
                return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"server at {address} not reachable")
