"""Fork-server worker spawn: preforked zygote templates + prestart policy.

Reference analog: ``src/ray/raylet/worker_pool.h:354`` ``PrestartWorkers``
(the reference keeps a pool of started-but-idle workers sized by lease
demand) combined with the CPython ``forkserver`` / Android zygote
pattern: per (node, runtime-env key) ONE long-lived *template* process
boots, preloads the heavy import set (ray_tpu runtime, serialization,
optionally user ``py_modules``), then answers fork requests over a
framed-RPC control pipe — every subsequent worker is an ``os.fork()``
away instead of a cold interpreter start plus imports.

JAX fork-safety rule (load-bearing): the template must NEVER initialize
an XLA device backend. Forking a process that holds live device runtime
state (driver threads, mapped HBM control structures) is undefined —
children would share the parent's backend handles. Templates therefore
only *import*; devices attach post-fork in the child, exactly as they
would in a cold-spawned worker. The template checks
:func:`jax_backends_initialized` before every fork and refuses to serve
if a preloaded user module broke the rule (the pool then cold-spawns).

Fallback contract: every failure in this file degrades to the status
quo. Template not yet warm → cold spawn. Template died (or the chaos
tier injected ``kill_template``) → cold spawn + background respawn of
the template. The worker a fork produces is indistinguishable from a
cold-spawned one: it re-runs the normal ``Worker()`` boot, so it dials
its OWN raylet/GCS channels and carries no fault-injection state from
the template (which never loads any).

Config flags (``ray_tpu/utils/config.py``, env ``RAY_TPU_PRESTART_*``):
``prestart_enabled``, ``prestart_min_workers``,
``prestart_spawn_threshold``, ``prestart_policy_interval_s``,
``prestart_idle_timeout_s``, ``prestart_fork_timeout_s``,
``prestart_max_forks_per_tick``, ``prestart_max_templates``.

Demand gate: a template is only created once an env key accumulates
``prestart_spawn_threshold`` spawn requests (or ``warm()`` is called, or
``prestart_min_workers`` > 0). Below the threshold every request
cold-spawns with zero added cost — a pool that spawns three workers and
exits never pays the template's interpreter start + preload imports,
while an actor fan-out crosses the threshold inside its first wave.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time

from ray_tpu.runtime.rpc import recv_msg, send_msg

# Environment variable carrying the control-pipe fd into the template.
ZYGOTE_FD_ENV = "RAY_TPU_ZYGOTE_FD"

# Set in a forked CHILD by _child_after_fork (test probe: a worker task
# can import this module and verify it was forked, that the template's
# control fd is closed, and which template it came from).
CHILD_INFO: dict | None = None


def jax_backends_initialized() -> bool:
    """True iff this process holds a LIVE XLA backend (not merely an
    imported jax module — importing is fork-safe, initialized device
    runtimes are not)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is not None and getattr(xb, "_backends", None):
            return True
    except Exception:  # noqa: BLE001 - jax internals moved; assume unsafe
        return True
    return False


class ForkedProc:
    """Popen-shaped handle for a worker forked BY THE TEMPLATE (so not
    our child: no waitpid — liveness via signal 0, reaping happens in
    the template). Implements the subset of the Popen surface the pool,
    raylet, and memory monitor use."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            # exit code is unobservable from a non-parent; -1 matches
            # the "killed" convention every caller formats
            self.returncode = -1
            return self.returncode
        except PermissionError:
            return None   # alive under another uid (containers)
        return None

    def wait(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"forked-worker-{self.pid}", timeout)
            time.sleep(0.02)
        return self.returncode

    def send_signal(self, sig):
        if self.returncode is not None:
            return
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            self.returncode = -1

    def terminate(self):
        self.send_signal(signal.SIGTERM)

    def kill(self):
        self.send_signal(signal.SIGKILL)


# ----------------------------------------------------------------------
# raylet side: template handle + manager
# ----------------------------------------------------------------------

class ZygoteTemplate:
    """One template process for one runtime-env key. The control pipe is
    a unix socketpair carrying the same framed messages as every other
    channel (``rpc.send_msg``/``recv_msg``)."""

    def __init__(self, env_key: str, runtime_env: dict | None,
                 base_env: dict, log_dir: str | None):
        self.env_key = env_key
        self.runtime_env = runtime_env
        self.proc: subprocess.Popen | None = None
        self.sock: socket.socket | None = None
        self.ready = False
        self.lock = threading.Lock()   # serializes fork request/reply pairs
        self.last_used = time.monotonic()
        self._base_env = base_env
        self._log_dir = log_dir
        self.log_stem: str | None = None

    def start(self):
        parent, child = socket.socketpair()
        env = dict(self._base_env)
        env[ZYGOTE_FD_ENV] = str(child.fileno())
        if self.runtime_env:
            env["RAY_TPU_RUNTIME_ENV"] = json.dumps(self.runtime_env)
        stdout = stderr = None
        if self._log_dir:
            stem = f"zygote-{(self.env_key or 'default')[:12]}"
            base = os.path.join(self._log_dir, stem)
            try:
                stdout = open(base + ".out", "ab", buffering=0)
                stderr = open(base + ".err", "ab", buffering=0)
                self.log_stem = stem
            except OSError:
                if stdout is not None:
                    stdout.close()
                stdout = stderr = None
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.worker_main",
                 "--zygote"],
                env=env, cwd=os.getcwd(), pass_fds=(child.fileno(),),
                stdout=stdout, stderr=stderr)
        finally:
            if stdout is not None:
                stdout.close()
                stderr.close()
            child.close()
        self.sock = parent
        return self

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def poll_ready(self, timeout: float = 0.0) -> bool:
        """Non-blocking by default: the template announces readiness with
        one framed ``{"ready": True}`` after its preload finishes; until
        then every fork request falls back to cold spawn."""
        if self.ready:
            return True
        if self.sock is None or not self.alive():
            return False
        r, _, _ = select.select([self.sock], [], [], timeout)
        if not r:
            return False
        try:
            self.sock.settimeout(2.0)
            msg = recv_msg(self.sock)
            self.sock.settimeout(None)
        except (OSError, EOFError):
            return False
        self.ready = bool(msg.get("ready"))
        return self.ready

    def fork(self, *, worker_id: str, extra_env: dict,
             log_out: str | None, log_err: str | None,
             timeout: float) -> int:
        """Framed fork RPC; returns the child pid. Raises OSError on any
        transport failure — the caller treats the template as dead (a
        half-done fork request must not be retried on the same pipe:
        request/reply pairing would desync)."""
        with self.lock:
            self.last_used = time.monotonic()
            self.sock.settimeout(timeout)
            try:
                send_msg(self.sock, {"type": "fork",
                                     "worker_id": worker_id,
                                     "env": extra_env,
                                     "log_out": log_out,
                                     "log_err": log_err})
                reply = recv_msg(self.sock)
            finally:
                try:
                    self.sock.settimeout(None)
                except OSError:
                    pass
        if not reply.get("ok"):
            raise OSError(f"template refused fork: {reply.get('error')}")
        return int(reply["pid"])

    def status(self, timeout: float = 5.0) -> dict:
        """Test/observability probe: template pid, preloaded module
        count, and the JAX-safety invariant."""
        with self.lock:
            self.sock.settimeout(timeout)
            try:
                send_msg(self.sock, {"type": "status"})
                return recv_msg(self.sock)
            finally:
                try:
                    self.sock.settimeout(None)
                except OSError:
                    pass

    def close(self, kill: bool = True):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if self.proc is not None and kill:
            try:
                self.proc.terminate()
            except OSError:
                pass

    def reap(self, timeout: float = 2.0):
        if self.proc is None:
            return
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()


class PrestartManager:
    """Owned by the WorkerPool: env-key → template registry, the fork
    fast path ``fork_worker`` (returns None on ANY miss so the pool cold
    spawns), and counters the node-info endpoint exposes."""

    def __init__(self, pool):
        self._pool = pool
        self.templates: dict[str, ZygoteTemplate] = {}
        self.lock = threading.Lock()
        # env keys whose demand justified a template: explicit warm(),
        # prestart_spawn_threshold cumulative requests, or min_workers>0.
        # Once justified, a key stays justified — a dead template
        # respawns on the next request without re-counting.
        self._justified: set[str] = set()
        self._spawn_requests: dict[str, int] = {}
        self.stats = {"forked": 0, "cold_fallback": 0,
                      "below_threshold": 0,
                      "template_spawns": 0, "template_deaths": 0,
                      "fault_template_kills": 0}

    @property
    def enabled(self) -> bool:
        from ray_tpu.utils.config import get_config
        return get_config().prestart_enabled

    # -- template registry ---------------------------------------------

    def _base_env(self) -> dict:
        from ray_tpu.runtime.worker_pool import (_worker_pythonpath,
                                                 env_get_default)

        node = self._pool._node
        env = dict(os.environ)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        env.update({
            "RAY_TPU_RAYLET_HOST": node.address[0],
            "RAY_TPU_RAYLET_PORT": str(node.address[1]),
            "RAY_TPU_GCS_HOST": node.gcs_address[0],
            "RAY_TPU_GCS_PORT": str(node.gcs_address[1]),
            "RAY_TPU_STORE_NAME": node.store_name,
            "RAY_TPU_NODE_ID": node.node_id,
            "JAX_PLATFORMS": env_get_default("JAX_PLATFORMS", "cpu"),
            "PYTHONUNBUFFERED": "1",
        })
        if getattr(node, "log_dir", None):
            # forked children re-enter Worker() directly; the in-process
            # log capture reads this to find its stamped-file home
            env["RAY_TPU_LOG_DIR"] = node.log_dir
        env.pop("RAY_TPU_WORKER_ID", None)
        env.pop("RAY_TPU_RUNTIME_ENV", None)
        return env

    def _get_template(self, key: str, runtime_env: dict | None
                      ) -> ZygoteTemplate | None:
        """Live template for this env key, spawning/respawning as
        needed. Called under ``self.lock``."""
        t = self.templates.get(key)
        if t is not None and not t.alive():
            self.stats["template_deaths"] += 1
            t.close()
            t.reap(timeout=0.5)
            self.templates.pop(key, None)
            t = None
        if t is None:
            from ray_tpu.utils.config import get_config
            cap = max(1, get_config().prestart_max_templates)
            while len(self.templates) >= cap:
                # LRU-evict: mirrors the pool's env-keyed idle eviction —
                # a node cycling through many envs keeps the newest
                victim_key = min(self.templates,
                                 key=lambda k: self.templates[k].last_used)
                victim = self.templates.pop(victim_key)
                victim.close()
                victim.reap(timeout=0.5)
            try:
                node = self._pool._node
                t = ZygoteTemplate(key, runtime_env, self._base_env(),
                                   getattr(node, "log_dir", None)).start()
            except OSError:
                return None
            self.templates[key] = t
            self.stats["template_spawns"] += 1
        return t

    def justified(self, key: str = "") -> bool:
        """True once this env key's demand crossed the spawn threshold
        (or ``warm()`` pinned it). The prestart policy loop keys off
        this: a pool that never showed fork-server demand keeps the
        status-quo scheduler-driven spawning, with zero policy
        side-effects."""
        with self.lock:
            return key in self._justified

    def warm(self, runtime_env: dict | None = None
             ) -> ZygoteTemplate | None:
        """Explicitly spawn the template for this env key, bypassing the
        spawn-request threshold (marks the key demand-justified, so a
        later death respawns too). Returns the template — the caller
        polls ``poll_ready`` — or None when prestart is off / spawn
        failed."""
        if not self.enabled:
            return None
        from ray_tpu.runtime_env import env_key as _env_key

        key = _env_key(runtime_env)
        with self.lock:
            self._justified.add(key)
            return self._get_template(key, runtime_env)

    # -- the fork fast path --------------------------------------------

    def fork_worker(self, runtime_env: dict | None, worker_id: str,
                    log_out: str | None, log_err: str | None):
        """Try to produce a worker by forking the env-keyed template.
        Returns a ForkedProc, or None → the caller cold-spawns."""
        if not self.enabled:
            return None
        if (runtime_env or {}).get("container"):
            return None   # container workers exec inside an image
        from ray_tpu.runtime_env import env_key as _env_key
        from ray_tpu.utils.config import get_config

        key = _env_key(runtime_env)
        cfg = get_config()
        with self.lock:
            if key not in self._justified:
                n = self._spawn_requests.get(key, 0) + 1
                self._spawn_requests[key] = n
                if (n >= max(1, cfg.prestart_spawn_threshold)
                        or cfg.prestart_min_workers > 0):
                    self._justified.add(key)
                else:
                    # not enough cumulative demand to pay for a template
                    # yet: a pool that only ever spawns a handful of
                    # workers (one short-lived test cluster) never eats
                    # the template's interpreter start + preload bill
                    self.stats["below_threshold"] += 1
                    self.stats["cold_fallback"] += 1
                    return None
            t = self._get_template(key, runtime_env)
        if t is None:
            self.stats["cold_fallback"] += 1
            return None
        # chaos hook: a `kill_template` rule (method "fork_worker") in
        # the PR-1 fault plane kills the template at the worst moment —
        # mid-acquisition — to prove the cold-spawn fallback
        from ray_tpu.runtime import fault_injection as _fi
        if _fi.plane.active:
            action = _fi.plane.consult(
                "raylet", "send", f"zygote:{key or 'default'}",
                "fork_worker")
            if action == _fi.KILL_TEMPLATE and t.proc is not None:
                self.stats["fault_template_kills"] += 1
                try:
                    t.proc.kill()
                    t.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        if not t.poll_ready():
            # template still preloading (or just died): cold spawn now,
            # the template warms up in the background
            self.stats["cold_fallback"] += 1
            return None
        try:
            pid = t.fork(worker_id=worker_id, extra_env={},
                         log_out=log_out, log_err=log_err,
                         timeout=get_config().prestart_fork_timeout_s)
        except (OSError, EOFError, ValueError, KeyError):
            # transport failure mid-fork: the pipe may be desynced and a
            # child may or may not exist — kill the template (an orphan
            # child simply registers as an extra idle worker) and fall
            # back to a cold spawn under a FRESH worker id
            self.stats["cold_fallback"] += 1
            with self.lock:
                if self.templates.get(key) is t:
                    self.stats["template_deaths"] += 1
                    t.close()
                    t.reap(timeout=0.5)
                    self.templates.pop(key, None)
            return None
        self.stats["forked"] += 1
        return ForkedProc(pid)

    # -- observability + shutdown --------------------------------------

    def log_stems(self) -> dict:
        """stem -> pid of live templates, so the raylet's log monitor
        treats their capture files as live (not dead-worker leftovers)."""
        with self.lock:
            return {t.log_stem: t.proc.pid
                    for t in self.templates.values()
                    if t.log_stem is not None and t.proc is not None}

    def snapshot(self) -> dict:
        with self.lock:
            return {"templates": {k or "default": {
                        "pid": t.proc.pid if t.proc else None,
                        "ready": t.ready,
                        "alive": t.alive()}
                        for k, t in self.templates.items()},
                    **self.stats}

    def stop(self):
        with self.lock:
            templates = list(self.templates.values())
            self.templates.clear()
        for t in templates:
            t.close()
        for t in templates:
            t.reap()


# ----------------------------------------------------------------------
# template side: the zygote server loop (entered via
# ``python -m ray_tpu.runtime.worker_main --zygote``)
# ----------------------------------------------------------------------

_PRELOAD_MODULES = (
    # the worker boot's import closure — this is the cold-start cost a
    # fork skips
    "ray_tpu._private.shm_store",
    "ray_tpu.runtime.object_codec",
    "ray_tpu.runtime.rpc",
    "ray_tpu.runtime.refcount",
    "ray_tpu.runtime.fault_injection",
    "ray_tpu.runtime_env",
    "ray_tpu.runtime.worker_main",
    "ray_tpu.utils.exceptions",
    "ray_tpu.utils.config",
    "cloudpickle",
    "numpy",
)


def _preload() -> list[str]:
    import importlib

    loaded = []
    for name in _PRELOAD_MODULES:
        try:
            importlib.import_module(name)
            loaded.append(name)
        except Exception:  # noqa: BLE001 - optional module absent
            pass
    # user env prewarm: pip install / working_dir snapshot / py_modules
    # copies happen ONCE here (apply_paths is the additive, chdir-free
    # half of apply_runtime_env) so the per-child apply in Worker() hits
    # warm caches. User modules are NOT imported eagerly — import side
    # effects could initialize a backend and break the fork-safety rule.
    renv_raw = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if renv_raw:
        try:
            from ray_tpu.runtime_env import apply_paths
            apply_paths(json.loads(renv_raw))
        except Exception:  # noqa: BLE001 - child applies + reports errors
            pass
    return loaded


def _child_after_fork(ctrl: socket.socket, req: dict):
    """Runs in the forked CHILD, before any worker code: sever every
    inherited handle so the worker is indistinguishable from a cold
    spawn. Only then boot ``Worker()`` (which dials its own channels)."""
    global CHILD_INFO
    ctrl_fd = ctrl.fileno()
    template_pid = os.getppid()
    ctrl.close()   # the template's control pipe MUST not leak into workers
    # per-worker log capture (the cold path redirects via Popen; here
    # the child re-points its own stdio post-fork)
    for path, fd in ((req.get("log_out"), 1), (req.get("log_err"), 2)):
        if path:
            try:
                f = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                            0o644)
                os.dup2(f, fd)
                os.close(f)
            except OSError:
                pass
    os.environ["RAY_TPU_WORKER_ID"] = req["worker_id"]
    for k, v in (req.get("env") or {}).items():
        os.environ[k] = str(v)
    os.environ.pop(ZYGOTE_FD_ENV, None)
    # fresh per-process state: config rereads env, the fault plane
    # starts empty (the template never loads one, but the invariant is
    # enforced here, not assumed), RNG reseeds
    from ray_tpu.runtime import fault_injection as _fi
    _fi.reset_after_fork()
    from ray_tpu.utils.config import reset_config
    reset_config()
    import random
    random.seed(os.urandom(16))
    CHILD_INFO = {"template_pid": template_pid, "ctrl_fd": ctrl_fd}
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    from ray_tpu.runtime.worker_main import Worker
    Worker().run()


def zygote_main() -> int:
    """Template process main: preload, announce readiness, serve fork
    requests. SINGLE-THREADED by design — ``os.fork()`` from a process
    with live threads inherits locked locks; the reap of exited children
    happens inline between control-pipe polls instead of on a thread."""
    fd = int(os.environ[ZYGOTE_FD_ENV])
    ctrl = socket.socket(fileno=fd)
    # SIGTERM = raylet shutdown: exit without touching children (live
    # workers outlive their template; the raylet owns THEIR lifecycle)
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    loaded = _preload()
    if jax_backends_initialized():
        # a preloaded module broke the fork-safety rule: refuse service
        # (the manager cold-spawns everything) rather than fork a live
        # XLA backend into children
        try:
            send_msg(ctrl, {"ready": False,
                            "error": "jax backend initialized in template"})
        except OSError:
            pass
        return 1
    try:
        send_msg(ctrl, {"ready": True, "pid": os.getpid()})
    except OSError:
        return 1
    while True:
        # reap exited children (non-blocking: they are OUR children even
        # though the raylet manages their lifecycle)
        try:
            while os.waitpid(-1, os.WNOHANG)[0] != 0:
                pass
        except ChildProcessError:
            pass
        r, _, _ = select.select([ctrl], [], [], 0.5)
        if not r:
            continue
        try:
            req = recv_msg(ctrl)
        except (OSError, EOFError):
            return 0   # raylet closed the pipe: shut down
        kind = req.get("type")
        if kind == "fork":
            if jax_backends_initialized():
                send_msg(ctrl, {"ok": False,
                                "error": "jax backend initialized"})
                continue
            pid = os.fork()
            if pid == 0:
                try:
                    _child_after_fork(ctrl, req)
                finally:
                    os._exit(0)
            try:
                send_msg(ctrl, {"ok": True, "pid": pid})
            except OSError:
                return 0
        elif kind == "status":
            send_msg(ctrl, {
                "ok": True, "pid": os.getpid(), "preloaded": loaded,
                "jax_imported": "jax" in sys.modules,
                "jax_backends_initialized": jax_backends_initialized(),
                "threads": threading.active_count()})
        elif kind == "exit":
            return 0
