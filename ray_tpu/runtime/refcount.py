"""Distributed object reference counting — the process-local half.

Reference analog: ``src/ray/core_worker/reference_count.h:61-115`` — the
reference tracks owners and borrowers per ObjectRef and releases objects
when every reference goes out of scope. The TPU-native redesign keeps the
same *capability* with a centralized protocol that matches this runtime's
centralized object directory (``runtime/gcs.py``):

- Every process (driver or worker) counts live ``ObjectRef`` instances per
  object id. Transitions (0→held, held→0) are flushed in batches to the
  GCS, which sums per-client holds, in-flight task pins, and
  contained-in edges; at zero, the GCS releases the primary copy on every
  node that registered a location.
- Submitting a task pins its argument objects under the task id (the
  owner's flush carries the pin); the executing worker releases the pin
  after the task finishes (``pin_releases``), covering normal, actor, and
  legacy submission paths uniformly.
- Serializing a value that *contains* ObjectRefs (a put, a task return)
  records contains-edges: the outer object holds a reference on each
  inner one until the outer itself is released (reference: borrower /
  contained-in tracking, ``reference_count.h:67``).

The counter is a process-global singleton: ``ObjectRef.__init__`` /
``__del__`` feed it directly, so it works in the driver, in pool workers
executing tasks, and in nested in-worker runtimes alike. ``__del__``
never takes the lock (a GC pass can fire inside a locked section): death
notices go through a lock-free deque drained on the next flush.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

# package root for callsite capture: frames under this directory are
# runtime internals, the first frame OUTSIDE it is the user's call site
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep
# filename -> is-internal memo, and (filename, lineno) -> "file:line"
# interning: a put/submit loop hits the same callsite every iteration,
# so the steady-state capture is two dict probes, no string building
_internal_files: dict[str, bool] = {}
_callsite_strings: dict[tuple, str] = {}
# bound once: note_owned sits on the put/submit hot path, fenced by
# memory_accounting_overhead_ratio in ci/perf_gate.py
_time_time = time.time


def capture_callsite() -> str | None:
    """First stack frame outside the ray_tpu package, as ``file:line``.

    Raw ``sys._getframe`` walk — no traceback/inspect object allocation
    — with memoized per-file classification and interned result
    strings, because this sits on the owner-side put/submit path and is
    fenced by ``memory_accounting_overhead_ratio`` in ci/perf_gate.py."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover - interpreter without frames
        return None
    # memo first, loop-free: strings only ever holds EXTERNAL frames, so
    # a hit on the immediate caller skips classification AND the walk.
    # Key on (code, lasti) — f_lineno is COMPUTED per access (line-table
    # walk), f_lasti is a plain slot.
    site = _callsite_strings.get((f.f_code, f.f_lasti))
    if site is not None:
        return site
    return _capture_walk(f)


def _capture_walk(f) -> str | None:
    """Slow path of :func:`capture_callsite`: classify and walk frames
    until the first one outside the package, memoizing as it goes."""
    strings = _callsite_strings
    imap = _internal_files
    for _ in range(24):
        if f is None:
            return None
        code = f.f_code
        key = (code, f.f_lasti)
        site = strings.get(key)
        if site is not None:
            return site
        fn = code.co_filename
        internal = imap.get(fn)
        if internal is None:
            internal = fn.startswith(_PKG_DIR) or "importlib" in fn
            if len(imap) < 4096:
                imap[fn] = internal
        if not internal:
            site = f"{fn}:{f.f_lineno}"
            if len(strings) < 16384:
                strings[key] = site
            return site
        f = f.f_back
    return None


class RefCounter:
    """Process-local reference table + pending flush state."""

    def __init__(self):
        self._lock = threading.Lock()
        # flusher wakeup: set by every lock-taking mutator so the flush
        # loops can BLOCK instead of polling (2,000 idle workers polling
        # at 5 Hz thrashed the host scheduler in the envelope run).
        # on_destroyed cannot signal (it runs in __del__, where taking
        # the Event's internal lock could deadlock mid-GC) — waiters
        # treat a non-empty dead deque as an immediate wakeup instead.
        self._signal = threading.Event()
        self._counts: dict[str, int] = {}       # oid hex -> live instances
        self._dead: deque = deque()             # oid hex death notices
        self._dirty: set[str] = set()           # count changed since flush
        self._flushed_held: set[str] = set()    # what the sink believes
        self._pins: list[tuple[str, list[str]]] = []   # (task_id, oids)
        self._pin_releases: list[str] = []              # task ids
        self._contains: list[tuple[str, list[str]]] = []
        # serialization capture: thread-local list appended to by
        # ObjectRef.__reduce__ while a capture scope is active
        self._tl = threading.local()
        # deserialize-tracking epoch: bumped on every on_created so
        # callers can detect "refs were constructed during this block"
        self._created_epoch = 0
        # local-mode immediate release callback (no flusher): called with
        # the oid hex when its count drops to zero
        self._local_release_cb = None
        # process-wide release hooks: called (outside the lock) with the
        # oids whose local count dropped to zero in a flush window —
        # the owner's in-process memory store evicts through this, no
        # matter which loop (driver or worker) drains the counter
        self._release_hooks: list = []
        # serialization hook: called with the oid hex of every ObjectRef
        # pickled in this process (any path — task args, puts, client
        # channels); the owner memory store promotes through it so a
        # ref shipped off-process always has a cluster-visible object
        self._serialize_hooks: list = []
        # -- memory plane: owner-side object accounting ----------------
        # oid hex -> (size_bytes, callsite, created_ts) for objects this
        # process OWNS (its puts + its submitted tasks' returns). Fed by
        # note_owned from the owning creation sites only — never from
        # on_created, which fires for every ObjectRef construction
        # including borrows and deserializes.
        self._owned: dict[str, tuple] = {}
        # last wall time this process saw ref churn (a non-empty flush
        # or a new owned object) — the leak detector's idle-owner signal
        self.last_activity: float = time.time()

    # ------------------------------------------------------------------
    # instance tracking (ObjectRef hooks)
    # ------------------------------------------------------------------

    def on_created(self, oid_hex: str):
        with self._lock:
            c = self._counts.get(oid_hex, 0)
            self._counts[oid_hex] = c + 1
            self._created_epoch += 1
            if c == 0:
                self._dirty.add(oid_hex)
                signal = True
            else:
                signal = False
        # is_set guard: Event.set() takes the Event's condition lock and
        # notifies even when already set — at 10k+ ref creations/s that
        # lock+notify per ref measurably stalls the submitting thread on
        # a small host (the flusher clears the flag only when it drains)
        if signal and not self._signal.is_set():
            self._signal.set()

    def on_destroyed(self, oid_hex: str):
        # lock-free: __del__ may run mid-GC inside a locked section
        self._dead.append(oid_hex)

    def _drain_dead_locked(self):
        zeroed = []
        while True:
            try:
                oid_hex = self._dead.popleft()
            except IndexError:
                break
            c = self._counts.get(oid_hex, 0) - 1
            if c <= 0:
                self._counts.pop(oid_hex, None)
                self._dirty.add(oid_hex)
                zeroed.append(oid_hex)
            else:
                self._counts[oid_hex] = c
        return zeroed

    # ------------------------------------------------------------------
    # serialization capture (contains-edges / nested task args)
    # ------------------------------------------------------------------

    class _Capture:
        def __init__(self, counter: "RefCounter"):
            self._counter = counter
            self.oids: set[str] = set()
            self._prev = None

        def add(self, oid_hex: str):
            self.oids.add(oid_hex)

        def __enter__(self):
            tl = self._counter._tl
            self._prev = getattr(tl, "capture", None)
            tl.capture = self
            return self

        def __exit__(self, *exc):
            self._counter._tl.capture = self._prev
            return False

    def capture(self) -> "RefCounter._Capture":
        """Scope that collects the oid of every ObjectRef serialized
        (``__reduce__``-ed) on this thread — puts record contains-edges,
        task submission records nested arg pins from it."""
        return RefCounter._Capture(self)

    def note_serialized(self, oid_hex: str):
        cap = getattr(self._tl, "capture", None)
        if cap is not None:
            cap.add(oid_hex)
        for hook in self._serialize_hooks:
            try:
                hook(oid_hex)
            except Exception:  # noqa: BLE001 - promotion is best-effort
                pass

    def add_serialize_hook(self, cb):
        self._serialize_hooks.append(cb)

    def remove_serialize_hook(self, cb):
        if cb in self._serialize_hooks:
            self._serialize_hooks.remove(cb)

    def add_release_hook(self, cb):
        self._release_hooks.append(cb)

    def remove_release_hook(self, cb):
        if cb in self._release_hooks:
            self._release_hooks.remove(cb)

    def count(self, oid_hex: str) -> int:
        """Current local instance count (GIL-atomic dict read)."""
        return self._counts.get(oid_hex, 0)

    # ------------------------------------------------------------------
    # memory plane: owned-object metadata + ownership snapshots
    # ------------------------------------------------------------------

    def note_owned(self, oid_hex: str, size: int,
                   callsite: str | None = None):
        """Record owner-side metadata for an object this process created
        (a put, or a submitted task's return). Size may be 0 when not
        yet known (task returns) — ``note_owned_size`` backfills it."""
        # single dict store + attribute store, both GIL-atomic: no lock
        # on the put/submit hot path (ownership_snapshot reads with a
        # retry loop instead). A pop racing in take_flush cannot
        # resurrect an entry — creation always precedes the ref's death.
        now = _time_time()
        self._owned[oid_hex] = (size or 0, callsite, now)
        self.last_activity = now

    def note_owned_here(self, oid_hex: str, size: int):
        """``note_owned`` with the callsite capture INLINED: one method
        call instead of two on the put hot path (the fenced overhead
        budget is ~400ns; a second Python call frame is ~15% of it).
        Captures the caller's caller — same depth convention as
        ``capture_callsite`` invoked from the same spot."""
        try:
            f = sys._getframe(2)
        except ValueError:  # pragma: no cover
            f = None
        site = None
        if f is not None:
            site = _callsite_strings.get((f.f_code, f.f_lasti))
            if site is None:
                site = _capture_walk(f)
        now = _time_time()
        self._owned[oid_hex] = (size or 0, site, now)
        self.last_activity = now

    def note_owned_size(self, oid_hex: str, size: int):
        """Backfill the byte size of an owned object once it is known
        (task returns report sizes after execution, not at submit)."""
        if not size:
            return
        with self._lock:
            ent = self._owned.get(oid_hex)
            if ent is not None and not ent[0]:
                self._owned[oid_hex] = (int(size), ent[1], ent[2])

    def owned_meta(self, oid_hex: str):
        """(size, callsite, created_ts) for an owned oid, else None."""
        return self._owned.get(oid_hex)

    def ownership_snapshot(self, max_entries: int = 512) -> dict:
        """Per-process ownership table for the ``mem/owners/<proc>``
        metrics annex: largest-first owned entries (capped), process
        totals, and the idle-owner signal. Entries are
        ``[oid, size, callsite, created_ts]``."""
        now = time.time()
        for _ in range(4):
            # note_owned writes lock-free; retry if a resize lands
            # mid-iteration, then fall back to excluding writers
            try:
                ents = [(oid, m[0], m[1], m[2])
                        for oid, m in self._owned.items()]
                break
            except RuntimeError:
                continue
        else:
            with self._lock:
                ents = [(oid, m[0], m[1], m[2])
                        for oid, m in self._owned.items()]
        refs_held = len(self._counts)
        last = self.last_activity
        ents.sort(key=lambda e: -e[1])
        owned_bytes = 0
        for e in ents:
            owned_bytes += e[1]
        truncated = max(0, len(ents) - max_entries)
        return {
            "entries": [[oid, s, cs, ts]
                        for oid, s, cs, ts in ents[:max_entries]],
            "owned": len(ents),
            "owned_bytes": owned_bytes,
            "refs_held": refs_held,
            "last_activity": last,
            "truncated": truncated,
            "ts": now,
        }

    def created_epoch(self) -> int:
        """Monotone counter of ObjectRef constructions in this process;
        callers compare before/after a deserialize to decide whether a
        synchronous flush is needed (borrower registration). Lock-free:
        a single int read is GIL-atomic, and callers only compare for
        inequality across their own critical section."""
        return self._created_epoch

    # ------------------------------------------------------------------
    # task pins + contains edges
    # ------------------------------------------------------------------

    def add_task_pins(self, task_id: str, oids: list[str]):
        if not oids:
            return
        with self._lock:
            self._pins.append((task_id, list(oids)))
        if not self._signal.is_set():
            self._signal.set()

    def release_task_pin(self, task_id: str):
        with self._lock:
            self._pin_releases.append(task_id)
        if not self._signal.is_set():
            self._signal.set()

    def add_contains(self, outer_hex: str, inner_hexes) -> None:
        inner = [h for h in inner_hexes if h != outer_hex]
        if not inner:
            return
        with self._lock:
            self._contains.append((outer_hex, inner))
        if not self._signal.is_set():
            self._signal.set()

    # ------------------------------------------------------------------
    # flushing
    # ------------------------------------------------------------------

    def take_flush(self) -> dict | None:
        """Snapshot-and-clear the pending state as a ``ref_update``
        payload; None when there is nothing to send. Adds are computed
        before removes so an add+remove of the same oid inside one
        window coalesces away."""
        with self._lock:
            self._drain_dead_locked()
            add, remove, transient = [], [], []
            for oid_hex in self._dirty:
                held = self._counts.get(oid_hex, 0) > 0
                was = oid_hex in self._flushed_held
                if held and not was:
                    add.append(oid_hex)
                    self._flushed_held.add(oid_hex)
                elif not held and was:
                    remove.append(oid_hex)
                    self._flushed_held.discard(oid_hex)
                elif not held and not was:
                    # held-and-dropped entirely WITHIN this flush window
                    # (put-get-del loops): the GCS never saw the hold, but
                    # it still needs the decrement event or the object is
                    # never considered for release
                    transient.append(oid_hex)
            self._dirty.clear()
            pins, self._pins = self._pins, []
            rel, self._pin_releases = self._pin_releases, []
            contains, self._contains = self._contains, []
            # owner dropped its last local ref: the owned-metadata entry
            # goes with it (the GCS keeps size + holders for objects
            # that live on through borrowers)
            for oid_hex in remove:
                self._owned.pop(oid_hex, None)
            for oid_hex in transient:
                self._owned.pop(oid_hex, None)
            if add or remove or transient or pins or rel or contains:
                self.last_activity = time.time()
        if (remove or transient) and self._release_hooks:
            dead = remove + transient
            for hook in self._release_hooks:
                try:
                    hook(dead)
                except Exception:  # noqa: BLE001 - eviction is best-effort
                    pass
        if not (add or remove or transient or pins or rel or contains):
            return None
        return {"add": add, "remove": remove, "transient": transient,
                "pins": pins, "pin_releases": rel, "contains": contains}

    def wait_pending(self, timeout: float) -> bool:
        """Block until flush-worthy state likely exists, or ``timeout``.
        Returns True when a flush should run now. Death notices can't
        signal (see ``_signal``), so a non-empty dead deque counts as an
        immediate wakeup — the subsequent ``take_flush`` drains it."""
        if self._dead:
            self._signal.clear()
            return True
        if self._signal.wait(timeout):
            self._signal.clear()
            return True
        return bool(self._dead)

    def force_resync(self):
        """The GCS reaped this client (heartbeat gap) and dropped every
        hold it believed we had: re-register the full held set on the
        next flush."""
        with self._lock:
            self._flushed_held.clear()
            for oid_hex, c in self._counts.items():
                if c > 0:
                    self._dirty.add(oid_hex)
        self._signal.set()

    def restore_flush(self, payload: dict):
        """Re-queue a flush whose send failed so the deltas are not
        lost (a lost add risks premature release; a lost remove leaks)."""
        with self._lock:
            for oid_hex in payload.get("add", ()):
                # still held? resend on the next flush
                self._flushed_held.discard(oid_hex)
                self._dirty.add(oid_hex)
            for oid_hex in payload.get("remove", ()):
                self._flushed_held.add(oid_hex)
                self._dirty.add(oid_hex)
            for oid_hex in payload.get("transient", ()):
                # not held, not believed held: re-dirty so the next flush
                # re-emits the transient decrement
                self._dirty.add(oid_hex)
            self._pins[:0] = payload.get("pins", ())
            self._pin_releases[:0] = payload.get("pin_releases", ())
            self._contains[:0] = payload.get("contains", ())
        self._signal.set()

    # ------------------------------------------------------------------
    # local mode (in-process runtime: release immediately, no RPC)
    # ------------------------------------------------------------------

    def set_local_release(self, cb):
        """Install an immediate-release callback (local-mode runtime).
        While set, zero-count transitions call ``cb(oid_hex)`` from the
        poll loop instead of accumulating flush state."""
        with self._lock:
            self._local_release_cb = cb
        if cb is not None:
            _activate()
        else:
            _deactivate()

    def poll_local(self):
        """Drain death notices and fire the local release callback for
        oids that dropped to zero (called from the local runtime's
        dispatcher / store hooks)."""
        with self._lock:
            cb = self._local_release_cb
            if cb is None:
                return
            self._drain_dead_locked()
            zeroed = [h for h in self._dirty
                      if self._counts.get(h, 0) == 0]
            # positive transitions carry no local-mode action: clear all
            # so the dirty set stays bounded
            self._dirty.clear()
            for oid_hex in zeroed:
                self._owned.pop(oid_hex, None)
            if zeroed:
                self.last_activity = time.time()
        for oid_hex in zeroed:
            try:
                cb(oid_hex)
            except Exception:  # noqa: BLE001 - release is best-effort
                pass

    def reset(self):
        """Forget all state (runtime shutdown / test isolation)."""
        with self._lock:
            self._counts.clear()
            self._dead.clear()
            self._dirty.clear()
            self._flushed_held.clear()
            self._pins.clear()
            self._pin_releases.clear()
            self._contains.clear()
            self._local_release_cb = None
            self._release_hooks.clear()
            self._serialize_hooks.clear()
            self._owned.clear()


def flush_once(counter: "RefCounter", call, client_id: str, kind: str,
               force_heartbeat: bool = False) -> bool:
    """One flush round of the client protocol, shared by the driver and
    worker loops: take pending deltas, send ``ref_update``, requeue on
    failure, and re-sync the held set when the GCS says this client was
    reaped and resurrected. ``call(method, **kwargs)`` is the GCS RPC."""
    payload = counter.take_flush()
    if payload is None and not force_heartbeat:
        return False
    try:
        reply = call("ref_update", client_id=client_id, kind=kind,
                     **(payload or {}))
        if reply.get("resync"):
            counter.force_resync()
        return True
    except Exception:  # noqa: BLE001 - GCS unreachable: requeue deltas
        if payload:
            counter.restore_flush(payload)
        return False


# The process-global counter fed by ObjectRef lifecycle hooks.
global_counter = RefCounter()

# Tracking is armed only once a drain exists (a flusher claim or a
# local-mode release callback): processes that never drain (remote
# ray-client processes, ref_counting_enabled=False) must not accumulate
# per-ref state unboundedly. ObjectRefs constructed before activation
# are permanently untracked — safe: they simply never contribute.
_active = False


def is_active() -> bool:
    return _active


def _activate():
    global _active
    _active = True


def _deactivate():
    global _active
    _active = False

# One flush channel per process: a pool worker's Worker loop claims it
# first; a nested in-worker ClusterRuntime then piggybacks on it instead
# of double-reporting under a second client id (holder attribution must
# be consistent within a process).
_flusher_lock = threading.Lock()
_flusher_owner: str | None = None


def claim_flusher(owner: str) -> bool:
    global _flusher_owner
    with _flusher_lock:
        if _flusher_owner is not None and _flusher_owner != owner:
            return False
        _flusher_owner = owner
        _activate()
        return True


def release_flusher(owner: str):
    global _flusher_owner
    with _flusher_lock:
        if _flusher_owner == owner:
            _flusher_owner = None
            _deactivate()
