"""Per-node worker pool: spawn, registration handshake, idle caching,
death handling, and the memory-pressure kill policy.

Reference analog: ``src/ray/raylet/worker_pool.cc`` (spawn + registration
handshake + env-keyed idle caching + eviction beyond the soft limit) and
``worker_killing_policy_retriable_fifo.cc`` (the OOM victim policy). The
pool is a component OWNED by the raylet (``runtime/raylet.py``): the
raylet keeps scheduling/leases/actors and delegates worker lifecycle
here; task-retry decisions on worker death call back into the raylet's
queueing/error paths so the policy stays in one place.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.runtime.rpc import RpcServer, recv_msg, send_msg
from ray_tpu.utils.ids import WorkerID


@dataclass
class WorkerHandle:
    worker_id: str
    proc: subprocess.Popen | None = None
    conn: Any = None            # held task-channel socket
    send_lock: Any = None
    state: str = "starting"     # starting | idle | busy | leased | actor | dead
    # owner-facing task port (worker-lease protocol); leases hand this
    # address to the owner, which pushes tasks to it directly
    push_addr: tuple | None = None
    actor_id: str | None = None
    incarnation: int = 0
    current_task: dict | None = None
    acquired: dict = field(default_factory=dict)
    # set by the memory monitor right before a pressure kill so the death
    # handler stores OutOfMemoryError instead of WorkerCrashedError
    oom_killed: bool = False
    # captured stdout/stderr file paths (tailed by the raylet log
    # monitor and forwarded to drivers)
    log_out: str | None = None
    log_err: str | None = None
    dispatched_at: float = 0.0   # monotonic time the current task started
    # runtime-env identity this worker booted with; tasks only run on a
    # worker with a matching key (reference: (language, runtime_env)-
    # keyed worker caching in worker_pool.cc)
    env_key: str = ""
    # monotonic time of the last busy→idle transition; the prestart
    # policy evicts idle workers beyond the demand target older than
    # prestart_idle_timeout_s
    idle_since: float = 0.0
    # spawned via the zygote fork path (runtime/prestart.py)
    forked: bool = False


class WorkerPool:
    """Worker lifecycle for one raylet. ``node`` is the owning Raylet —
    the pool reads its identity/addresses and calls back into its
    scheduling (enqueue/release/kick) and error (store_task_error)
    paths."""

    BAD_ENV_TTL_S = 60.0

    def __init__(self, node, *, max_workers: int):
        from ray_tpu.runtime.prestart import PrestartManager

        self._node = node
        self.max_workers = max_workers
        self.workers: dict[str, WorkerHandle] = {}
        self.lock = threading.Lock()
        # fork-server templates (runtime/prestart.py): lazy — no process
        # is spawned until the first fork attempt
        self.prestart = PrestartManager(self)
        # actor-creation misses since the last policy tick: actors do
        # not flow through the lease queue, so take_idle_for_actor
        # misses are their demand signal to the prestart policy
        self._actor_demand = 0
        # why recent workers died, queried by lease owners on break
        # (bounded FIFO; reference: worker exit detail in death reports)
        self._death_info: dict[str, dict] = {}
        # env_key -> (error, when): envs whose setup failed — tasks fail
        # fast instead of driving a spawn/install/crash loop
        self._bad_envs: dict[str, tuple] = {}

    # ------------------------------------------------------------------
    # spawn + registration (reference: worker_pool.cc StartWorkerProcess
    # + RegisterWorker handshake)
    # ------------------------------------------------------------------

    def spawn(self, runtime_env: dict | None = None) -> WorkerHandle:
        from ray_tpu.runtime_env import env_key as _env_key

        node = self._node
        worker_id = WorkerID.from_random().hex()
        env = dict(os.environ)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        if runtime_env:
            env["RAY_TPU_RUNTIME_ENV"] = json.dumps(runtime_env)
        env.update({
            "RAY_TPU_RAYLET_HOST": node.address[0],
            "RAY_TPU_RAYLET_PORT": str(node.address[1]),
            "RAY_TPU_GCS_HOST": node.gcs_address[0],
            "RAY_TPU_GCS_PORT": str(node.gcs_address[1]),
            "RAY_TPU_STORE_NAME": node.store_name,
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_NODE_ID": node.node_id,
            # workers never touch the TPU tunnel unless told to
            "JAX_PLATFORMS": env_get_default("JAX_PLATFORMS", "cpu"),
            # stdout is a capture file now; without this, prints sit in
            # the worker's block buffer instead of reaching the driver
            "PYTHONUNBUFFERED": "1",
        })
        # Capture paths first: both spawn paths share them (the cold
        # path opens+dups them into Popen; a forked child opens them
        # itself post-fork)
        log_dir = getattr(node, "log_dir", None)
        log_out = log_err = None
        if log_dir:
            # the worker's in-process tee writes its stamped .log file
            # here; the Popen fd redirect below still owns .out/.err for
            # C-level / interpreter-crash output the tee can't see
            env["RAY_TPU_LOG_DIR"] = log_dir
            base = os.path.join(log_dir, f"worker-{worker_id[:12]}")
            log_out, log_err = base + ".out", base + ".err"
        # fork fast path: an os.fork() of the preloaded env-keyed
        # template instead of a cold interpreter start; any miss
        # (disabled, template warming/dead, container env) returns None
        # and the cold path below runs unchanged
        fork_proc = self.prestart.fork_worker(runtime_env, worker_id,
                                              log_out, log_err)
        if fork_proc is not None:
            handle = WorkerHandle(worker_id=worker_id, proc=fork_proc,
                                  env_key=_env_key(runtime_env),
                                  forked=True)
            handle.log_out, handle.log_err = log_out, log_err
            with self.lock:
                self.workers[worker_id] = handle
            return handle
        cmd = [sys.executable, "-m", "ray_tpu.runtime.worker_main"]
        container = (runtime_env or {}).get("container")
        if container:
            # CONTAINER worker (reference: runtime_env/container.py —
            # the worker process itself runs in the image). Host
            # networking + host IPC keep the raylet channel and the
            # /dev/shm object store working unchanged.
            from ray_tpu.runtime_env import (container_command,
                                             find_container_runtime)

            runtime = find_container_runtime()
            if runtime is None:
                # fail every queued task for this env fast instead of a
                # spawn/crash loop (same path a worker-side env setup
                # failure takes); the spawned stand-in exits immediately
                # and the monitor reaps it like any dead worker
                from ray_tpu.runtime_env import env_key as _ek

                node.rpc_runtime_env_failed(
                    None, None, key=_ek(runtime_env),
                    error="runtime_env.container requested but no "
                          "docker/podman on PATH")
                cmd = [sys.executable, "-c", "raise SystemExit(1)"]
            else:
                cmd = container_command(
                    container,
                    ["python", "-m", "ray_tpu.runtime.worker_main"],
                    env, runtime=runtime)
        # Capture worker stdout/stderr into the raylet's log dir; the
        # raylet's log monitor tails these and forwards lines to the
        # driver (reference: worker logs -> session dir -> log_monitor)
        stdout = stderr = None
        if log_out:
            try:
                stdout = open(log_out, "ab", buffering=0)
                stderr = open(log_err, "ab", buffering=0)
            except OSError:
                # disk-full/permission: run uncaptured, don't leak the
                # half-opened fd
                if stdout is not None:
                    stdout.close()
                stdout = stderr = None
                log_out = log_err = None
        try:
            proc = subprocess.Popen(cmd, env=env, cwd=os.getcwd(),
                                    stdout=stdout, stderr=stderr)
        finally:
            # Popen dup'd the fds; our handles can close immediately
            if stdout is not None:
                stdout.close()
                stderr.close()
        handle = WorkerHandle(worker_id=worker_id, proc=proc,
                              env_key=_env_key(runtime_env))
        handle.log_out, handle.log_err = log_out, log_err
        with self.lock:
            self.workers[worker_id] = handle
        return handle

    def register(self, conn, send_lock, *, worker_id, push_addr=None):
        """Registration handshake; the connection becomes the raylet→worker
        task channel and worker→raylet completion stream. Runs the
        channel's read loop and returns ``RpcServer.HELD``."""
        node = self._node
        with self.lock:
            handle = self.workers.get(worker_id)
            if handle is None:   # externally started worker (tests)
                handle = WorkerHandle(worker_id=worker_id)
                self.workers[worker_id] = handle
            if push_addr is not None:
                handle.push_addr = tuple(push_addr)
        # the registration ack MUST be the channel's first message: only
        # AFTER it is on the wire may other threads see handle.conn —
        # an actor-delivery thread polling for the conn could otherwise
        # inject create_actor ahead of the ack and fail the handshake
        send_msg(conn, {"registered": True}, send_lock)
        with self.lock:
            handle.conn = conn
            handle.send_lock = send_lock
            if handle.state == "starting":
                # actor-designated workers keep their "actor" state — the
                # dispatcher must never hand them normal tasks
                handle.state = "idle"
                handle.idle_since = time.monotonic()
        node._kick_dispatch()
        try:
            while not node._stopping:
                try:
                    msg = recv_msg(conn)
                except (OSError, EOFError, Exception):
                    break
                self._on_worker_msg(handle, msg)
        finally:
            node.release_conn(conn)   # held channel finished
            self.on_worker_gone(handle)
        return RpcServer.HELD

    def _on_worker_msg(self, w: WorkerHandle, msg: dict):
        node = self._node
        kind = msg.get("type")
        if kind == "task_done":
            self._finish_task(w)
        elif kind == "actor_ready":
            # batched ack: the node's flusher coalesces a creation
            # flood's readies into one actors_ready frame per linger
            node.queue_actor_ready(
                msg["actor_id"],
                list(w.push_addr) if w.push_addr else None)
        elif kind == "actor_creation_failed":
            with node._gcs_lock:
                node._gcs.call("actor_failed", actor_id=msg["actor_id"],
                               reason=msg.get("reason", "creation failed"))

    def _finish_task(self, w: WorkerHandle):
        node = self._node
        with self.lock:
            w.current_task = None
        if w.state == "busy":
            # actor workers keep their acquisition for their LIFETIME
            # (released on death/kill); only per-task resources return here
            node._release(w.acquired)
            w.acquired = {}
            w.idle_since = time.monotonic()
            w.state = "idle"
        node._kick_dispatch()

    # ------------------------------------------------------------------
    # death handling (reference: NodeManager worker failure path)
    # ------------------------------------------------------------------

    def on_worker_gone(self, w: WorkerHandle):
        """Worker process/channel died: record death info, reclaim store
        refs, and hand the in-flight task to the raylet's retry/error
        policy."""
        node = self._node
        if node._stopping:
            return
        info = {"oom_killed": w.oom_killed}
        if w.proc is not None:
            info["exit_code"] = w.proc.poll()
        # SIGKILL leaves no flight-recorder dump: the raw .err redirect
        # holds the interpreter-level last words (and the fault plane's
        # injected-crash marker) — harvest them into death info so the
        # lease/actor layers can surface a typed, attributed error
        info.update(_last_words(w.log_err))
        with self.lock:
            if w.state == "dead":
                return  # channel reader and monitor both report deaths
            prior_state = w.state
            w.state = "dead"
            self.workers.pop(w.worker_id, None)
            self._death_info[w.worker_id] = info
            while len(self._death_info) > 256:
                self._death_info.pop(next(iter(self._death_info)))
        # reclaim created-but-unsealed allocations and pinned read refs of
        # the dead worker only (live writers/readers are untouched)
        if w.proc is not None and w.proc.pid:
            node.store.evict_orphans(w.proc.pid)
            node.store.release_pid(w.proc.pid)
        task = w.current_task
        node._release(w.acquired)
        w.acquired = {}
        if prior_state == "actor" and w.actor_id is not None:
            reason = f"actor worker {w.worker_id[:8]} died"
            if info.get("crash_point"):
                reason += f" at crash point {info['crash_point']}"
            try:
                with node._gcs_lock:
                    node._gcs.call(
                        "actor_failed", actor_id=w.actor_id,
                        reason=reason)
            except Exception:  # noqa: BLE001 - gcs may be shutting down
                pass
        elif task is not None:
            node._retry_or_fail_dead_worker_task(w, task)
        # proactive respawn: a crashed worker whose slot had parked lease
        # waiters (or a leased channel an owner will re-acquire) should
        # not wait for the next demand-driven spawn — kick the dispatch
        # loop so _serve_lease_waiters spawns/grants a replacement now
        node._kick_dispatch()

    def death_info(self, worker_id: str) -> dict | None:
        with self.lock:
            return self._death_info.get(worker_id)

    # ------------------------------------------------------------------
    # failed runtime envs (fail fast instead of spawn/install/crash loops)
    # ------------------------------------------------------------------

    def mark_bad_env(self, key: str, error: str):
        self._bad_envs[key] = (error, time.monotonic())

    def bad_env_error(self, runtime_env) -> str | None:
        from ray_tpu.runtime_env import env_key as _env_key

        hit = self._bad_envs.get(_env_key(runtime_env))
        if hit is None:
            return None
        error, at = hit
        if time.monotonic() - at > self.BAD_ENV_TTL_S:
            return None   # stale: the env may be fixable (cache purged)
        return error

    # ------------------------------------------------------------------
    # idle caching + eviction (reference: worker_pool.cc PopWorker +
    # idle eviction beyond the cached-soft-limit)
    # ------------------------------------------------------------------

    def idle_worker(self, runtime_env: dict | None = None
                    ) -> WorkerHandle | None:
        """Grab an idle registered worker WITH a matching runtime-env
        key; spawn one for this env when under the cap. At the cap, an
        idle worker with a DIFFERENT env key is evicted to make room —
        otherwise a full pool of mismatched-env workers starves the task
        forever (reference: worker_pool.cc kills idle workers beyond the
        cached-soft-limit when a lease needs a different runtime_env)."""
        from ray_tpu.runtime_env import env_key as _env_key

        key = _env_key(runtime_env)
        evict = None
        with self.lock:
            n_alive = 0
            incoming = False  # replacement with this env already booting?
            for w in self.workers.values():
                # DEDICATED actor workers are not pool capacity: they
                # hold their own acquired resources for their lifetime.
                # Counting them against max_workers starves every task
                # on an actor-heavy node (500 idle actors on a 4-cpu
                # node left ZERO task workers spawnable at the envelope
                # tier — reference: worker_pool.cc caps the POOL, not
                # dedicated workers).
                if w.state in ("idle", "busy", "starting", "leased"):
                    n_alive += 1
                if w.state == "starting" and w.env_key == key:
                    incoming = True
                if (w.state == "idle" and w.conn is not None
                        and w.env_key == key):
                    w.state = "busy"
                    return w
            if incoming:
                # a matching worker is already on its way — evicting more
                # warm workers per dispatch retry would drain the whole
                # pool for one task
                return None
            spawn = n_alive < self.max_workers
            if not spawn:
                for w in self.workers.values():
                    if (w.state == "idle" and w.conn is not None
                            and w.env_key != key):
                        # not "dead": on_worker_gone must still run its
                        # cleanup (pop from registry, store refs, zombie
                        # reap) when the channel closes
                        w.state = "evicting"
                        evict = w
                        spawn = True
                        break
        if evict is not None:
            self._evict_async(evict)
        if spawn:
            self.spawn(runtime_env)
        return None

    def _evict_async(self, w: WorkerHandle):
        """Terminate an idle worker off the calling thread: a worker
        slow to honor SIGTERM must not stall dispatch (or the prestart
        policy tick) for every other queued task."""
        def _reap():
            try:
                if w.proc is not None:
                    w.proc.terminate()
                if w.conn is not None:
                    w.conn.close()
            except OSError:
                pass
            self.on_worker_gone(w)
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    w.proc.kill()

        threading.Thread(target=_reap, name="ray_tpu-evict",
                         daemon=True).start()

    def take_idle_for_actor(self, runtime_env: dict | None = None
                            ) -> WorkerHandle | None:
        """Dedicate an already-registered idle worker (matching env key)
        to an actor instead of spawning a fresh process — with the fork
        pool keeping idle workers warm this makes actor creation an RPC
        away (reference: PopWorker serving actor-creation leases from
        the started-worker pool). Gated on prestart_enabled so the
        legacy fresh-process-per-actor behavior is preserved when the
        subsystem is off."""
        if not self.prestart.enabled:
            return None
        from ray_tpu.runtime_env import env_key as _env_key

        key = _env_key(runtime_env)
        with self.lock:
            for w in self.workers.values():
                if (w.state == "idle" and w.conn is not None
                        and w.env_key == key):
                    w.state = "actor"
                    return w
            self._actor_demand += 1
        return None

    # ------------------------------------------------------------------
    # prestart policy (reference: worker_pool.h:354 PrestartWorkers —
    # lease-demand-driven warm pool + idle eviction beyond the target)
    # ------------------------------------------------------------------

    def prestart_policy_loop(self):
        from ray_tpu.utils.config import get_config

        node = self._node
        cfg = get_config()
        if not cfg.prestart_enabled:
            return
        while not node._stopping:
            node._interruptible_sleep(cfg.prestart_policy_interval_s)
            if node._stopping:
                return
            try:
                self._prestart_tick(cfg)
            except Exception:  # noqa: BLE001 - policy must never die
                pass

    def _prestart_tick(self, cfg):
        """One policy decision: predict demand from lease-queue + ready-
        queue depth, fork up to the deficit, evict idle workers beyond
        the target that outlived the idle timeout."""
        # The policy only acts where fork-server demand exists: the
        # default env key crossed the spawn threshold, or an explicit
        # warm floor is configured. Ungated, every transient queue blip
        # in a small short-lived pool would speculatively spawn workers
        # the scheduler's own demand spawning already covers.
        if (cfg.prestart_min_workers <= 0
                and not self.prestart.justified("")):
            return
        sched = self._node.scheduler
        with sched.cv:
            depth = len(sched.ready) + len(sched.lease_waiters)
        now = time.monotonic()
        with self.lock:
            # an actor-creation burst shows up as take_idle misses, not
            # queue depth — fold it in so the next wave of creations is
            # served by warm takeovers instead of per-actor forks
            depth += self._actor_demand
            self._actor_demand = 0
            idle = [w for w in self.workers.values()
                    if w.state == "idle" and w.conn is not None
                    and w.env_key == ""]
            n_starting = sum(1 for w in self.workers.values()
                             if w.state == "starting")
            n_alive = sum(1 for w in self.workers.values()
                          if w.state in ("idle", "busy", "starting",
                                         "leased"))
        want = min(max(depth, cfg.prestart_min_workers), self.max_workers)
        deficit = min(want - (len(idle) + n_starting),
                      self.max_workers - n_alive,
                      cfg.prestart_max_forks_per_tick)
        for _ in range(max(0, deficit)):
            self.spawn(None)
        if cfg.prestart_idle_timeout_s <= 0:
            return
        floor = max(want, cfg.prestart_min_workers)
        excess = len(idle) - floor
        if excess <= 0:
            return
        victims = []
        with self.lock:
            for w in sorted(idle, key=lambda w: w.idle_since):
                if len(victims) >= excess:
                    break
                if (w.state == "idle"
                        and now - w.idle_since
                        > cfg.prestart_idle_timeout_s):
                    w.state = "evicting"
                    victims.append(w)
        for w in victims:
            self._evict_async(w)

    # ------------------------------------------------------------------
    # observability targets (worker push ports serve stack dumps/profiles)
    # ------------------------------------------------------------------

    def push_targets(self, worker_id: str | None = None):
        with self.lock:
            return [(w.worker_id, w.push_addr)
                    for w in self.workers.values()
                    if w.push_addr is not None and w.state != "dead"
                    and (worker_id is None or w.worker_id == worker_id)]

    # ------------------------------------------------------------------
    # background loops (driven by the raylet's thread registry)
    # ------------------------------------------------------------------

    def monitor_loop(self):
        """Reap dead worker processes (reference: worker failure detection
        via socket + SIGCHLD in NodeManager)."""
        node = self._node
        while not node._stopping:
            time.sleep(0.1)
            with self.lock:
                dead = [w for w in self.workers.values()
                        if w.proc is not None and w.proc.poll() is not None
                        and w.state != "dead"]
            for w in dead:
                self.on_worker_gone(w)

    # --- memory monitor (reference: MemoryMonitor memory_monitor.h:52
    # driving the raylet's WorkerKillingPolicy — kill the newest retriable
    # task's worker first so forward progress is preserved) ---

    @staticmethod
    def host_memory_fraction() -> float:
        """Used fraction of host memory from /proc/meminfo (the reference
        also honors cgroup limits; host-level covers TPU-VM deployments)."""
        total = avail = None
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        break
        except OSError:
            return 0.0
        if not total or avail is None:
            return 0.0
        return 1.0 - avail / total

    def memory_monitor_loop(self, threshold: float, refresh_s: float):
        node = self._node
        while not node._stopping:
            node._interruptible_sleep(refresh_s)
            if node._stopping:
                return
            if self.host_memory_fraction() < threshold:
                continue
            if self.kill_one_for_memory():
                node._interruptible_sleep(1.0)  # let the kill take effect

    def kill_one_for_memory(self) -> bool:
        """Pick and kill one worker to relieve pressure. Policy (reference
        worker_killing_policy_retriable_fifo.cc): newest-started RETRIABLE
        task first (its re-execution is cheapest and guaranteed safe),
        then newest non-retriable task worker; actors are never chosen —
        their state is not re-executable (the reference's group-by-owner
        policy similarly deprioritizes them)."""
        with self.lock:
            # select AND kill inside the lock: a victim finishing its task
            # in between would take the SIGKILL for a brand-new task
            busy = [(w, w.current_task, w.dispatched_at)
                    for w in self.workers.values()
                    if w.state == "busy" and w.current_task is not None
                    and w.proc is not None]
            # leased workers are candidates too: their owner observes the
            # break, queries worker_death_info, and applies ITS OOM retry
            # budget (this raylet does not know the task)
            leased = [(w, None, w.dispatched_at)
                      for w in self.workers.values()
                      if w.state == "leased" and w.proc is not None]
            if not busy and not leased:
                return False
            busy.sort(key=lambda it: it[2])   # oldest-dispatched first
            leased.sort(key=lambda it: it[2])
            retriable = [it for it in busy
                         if it[1].get("max_retries", 0) > 0]
            # newest-dispatched first among: retriable (cheapest safe
            # re-run), then leased (owner-managed retry), then the rest
            victim = (retriable or leased or busy)[-1][0]
            victim.oom_killed = True
            try:
                victim.proc.kill()
            except OSError:
                victim.oom_killed = False  # a later crash is NOT an OOM
                return False
        return True

    # ------------------------------------------------------------------

    def stop(self):
        """Terminate every worker process (called from Raylet.stop after
        background loops have been joined)."""
        self.prestart.stop()
        with self.lock:
            workers = list(self.workers.values())
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    w.proc.kill()


def env_get_default(key: str, default: str) -> str:
    v = os.environ.get(key)
    return v if v else default


def _worker_pythonpath(current: str) -> str:
    """PYTHONPATH for spawned workers: the ray_tpu package root plus the
    inherited entries, minus directories that install a ``sitecustomize``
    hook — such hooks (e.g. a driver-side TPU tunnel plugin) eagerly import
    heavyweight runtimes and add seconds to EVERY worker spawn. Set
    RAY_TPU_WORKER_KEEP_SITE=1 to keep them (workers that must dial the
    TPU backend through the site hook)."""
    import ray_tpu
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))
    entries = [pkg_root]
    keep_site = os.environ.get("RAY_TPU_WORKER_KEEP_SITE") == "1"
    for p in current.split(os.pathsep):
        if not p or p == pkg_root:
            continue
        if not keep_site and os.path.exists(
                os.path.join(p, "sitecustomize.py")):
            continue
        entries.append(p)
    return os.pathsep.join(entries)


def _last_words(path: str | None, nbytes: int = 4096) -> dict:
    """Tail a dead worker's raw ``.err`` redirect: the last non-empty
    lines plus the injected crash-point name when the fault plane killed
    it (SIGKILL leaves no flight-recorder dump; the redirect is all
    there is)."""
    if not path:
        return {}
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            tail = f.read().decode("utf-8", "replace")
    except OSError:
        return {}
    lines = [ln.strip() for ln in tail.splitlines() if ln.strip()]
    if not lines:
        return {}
    out: dict = {"last_words": lines[-6:]}
    from ray_tpu.runtime import fault_injection as _fi

    for ln in reversed(lines):
        if _fi.CRASH_MARKER in ln:
            for part in ln.split():
                if part.startswith("point="):
                    out["crash_point"] = part[6:]
            break
    return out
