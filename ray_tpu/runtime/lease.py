"""Owner-side worker-lease protocol.

Reference: ``CoreWorkerDirectTaskSubmitter`` (``direct_task_transport.cc:134``
``RequestNewWorkerIfNeeded``, ``:191`` ``OnWorkerIdle``, ``:234``
``PushNormalTask``). The owner leases a worker slot from a raylet, then
pushes tasks DIRECTLY to the leased worker over a dedicated connection:

- the raylet schedules once per LEASE, not once per task — while the
  owner's queue for a resource shape is non-empty, tasks flow over the
  held connection with no scheduler hop (the reference's lease-reuse
  throughput win);
- the connection is the liveness channel: when the worker (or its node)
  dies, the owner's in-flight push fails SYNCHRONOUSLY and the task is
  retried or failed on the spot — replacing the round-1 time-based
  "presumed lost after a grace" heuristic that could double-submit slow
  but healthy tasks.

Placement-constrained tasks (placement groups, node affinity, spread)
keep the raylet-queue path — their placement is per-task by nature —
as do lease-infeasible fallbacks; the raylet's queue also keeps serving
its own internal retries.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from ray_tpu.runtime.rpc import ConnectionLost, RpcClient
from ray_tpu.util import metrics as _metrics

# owner-side lease stage timers (metrics plane): "acquire" is the full
# grant latency seen by a pusher (parking + spillback hops included);
# "push_rtt" is one pushed task group's round trip over the held lease
_lease_hist = _metrics.histogram(
    "ray_tpu_lease_owner_s", "owner-side lease stage latency",
    tag_keys=("stage",))
_h_acquire = _lease_hist.handle({"stage": "acquire"})
_h_push_rtt = _lease_hist.handle({"stage": "push_rtt"})


class Lease:
    """One granted worker lease = one dedicated connection to the worker's
    push port. Closing the connection returns the lease (the worker tells
    its raylet, which frees the slot)."""

    __slots__ = ("client", "worker_id", "node_id", "addr", "raylet_addr")

    def __init__(self, addr, worker_id: str, node_id: str, raylet_addr):
        self.addr = tuple(addr)
        # "owner" labels the owner↔worker push plane for fault injection
        self.client = RpcClient(self.addr, label="owner")
        self.worker_id = worker_id
        self.node_id = node_id
        self.raylet_addr = tuple(raylet_addr)  # the granting raylet
        # First request on the wire tags this connection as THE lease
        # channel on the worker side (its push port is shared with
        # observability and direct-actor clients, whose disconnects must
        # not release the lease). Fire-and-forget: the server handles a
        # connection's requests in order, so the tag lands before any
        # push; the reader thread consumes the reply.
        try:
            self.client.call_async("lease_attach")
        except BaseException:
            # attach failed (worker died mid-dial): close the dialed
            # socket + its reader thread before the caller's handback
            # path discards this half-constructed lease
            self.client.close()
            raise

    def close(self):
        self.client.close()


def _shape_key(task: dict) -> tuple:
    from ray_tpu.runtime_env import env_key

    res = tuple(sorted(task.get("resources", {}).items()))
    return (res, env_key(task.get("runtime_env")))


def _leasable(task: dict) -> bool:
    kind = task.get("strategy", {}).get("kind")
    pg = task.get("strategy", {}).get("pg_id")
    return not pg and kind in (None, "", "DEFAULT")


class LeaseManager:
    """Per-owner submission engine: one queue per resource shape, one
    pusher thread per held lease, legacy raylet-queue fallback."""

    def __init__(self, raylet_client: RpcClient, *,
                 legacy_submit: Callable[[dict], None],
                 on_task_failed: Callable[[dict, BaseException], None],
                 on_direct_results: Callable[[dict], None] | None = None,
                 max_leases_per_shape: int | None = None,
                 lease_block_s: float | None = None):
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        self._raylet = raylet_client
        self._legacy_submit = legacy_submit
        self._on_task_failed = on_task_failed
        # small task returns riding the push reply (owner-store path)
        self._on_direct_results = on_direct_results
        self._max_per_shape = (max_leases_per_shape
                               if max_leases_per_shape is not None
                               else cfg.max_leases_per_shape)
        self._lease_block_s = (lease_block_s if lease_block_s is not None
                               else cfg.lease_block_s)
        # flags lease_group_size / lease_pipeline_depth (class attrs
        # keep the measured defaults as documentation)
        self.GROUP_SIZE = cfg.lease_group_size
        self.PIPELINE_DEPTH = cfg.lease_pipeline_depth
        self._lock = threading.Lock()
        self._queues: dict[tuple, deque] = {}
        self._pushers: dict[tuple, int] = {}
        # pushers currently HOLDING a lease (vs acquiring/parked): sizes
        # fair-share grouping and gates spawn growth to actual capacity
        self._holding: dict[tuple, int] = {}
        self._in_flight: dict[str, tuple] = {}   # task_id -> (task, lease)
        self._stopping = False

    # ------------------------------------------------------------------

    def submit(self, task: dict):
        """Non-blocking: enqueue and make sure enough pushers are draining
        this shape's queue (one pusher == at most one lease == one task in
        flight, so pusher count scales concurrency up to the cap)."""
        if self._stopping or not _leasable(task):
            self._legacy_submit(task)
            return
        key = _shape_key(task)
        spawn = 0
        with self._lock:
            q = self._queues.setdefault(key, deque())
            q.append(task)
            active = self._pushers.get(key, 0)
            holding = self._holding.get(key, 0)
            # Spawn at most ONE prober, and only when every active pusher
            # already holds a lease: pool growth is GRANT-driven (a pusher
            # that acquires with surplus queue spawns the next prober in
            # _pusher), so the pool ramps one grant at a time up to the
            # cluster's real capacity instead of stampeding max_per_shape
            # threads at 4 lease slots — 60 parked probers per shape turn
            # the raylet's lease queue into the bottleneck. A drip-fed
            # shape still grows: each submit seeing all-holders-busy adds
            # exactly one prober.
            spawn = 1 if (active < self._max_per_shape
                          and active - holding <= 0) else 0
            if spawn:
                self._pushers[key] = active + 1
        for _ in range(max(spawn, 0)):
            threading.Thread(target=self._pusher, args=(key,),
                             name="ray_tpu-lease-pusher", daemon=True).start()

    def stop(self):
        """Stop pushers: no new work, wake blocked pushes by severing the
        lease connections, and never touch runtime state (store/raylet)
        again — shutdown munmaps the store under us otherwise."""
        self._stopping = True
        with self._lock:
            leases = [lease for _, lease in self._in_flight.values()
                      if lease is not None]
            self._queues.clear()
        for lease in leases:
            try:
                lease.close()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------

    def _pop(self, key: tuple):
        with self._lock:
            q = self._queues.get(key)
            if q:
                return q.popleft()
            return None

    PIPELINE_DEPTH = 2   # in-flight push GROUPS per lease (hides owner RTT)
    # max tasks packed into one push RPC: 64 measured ~20% faster than
    # 32 at 4 leases (fewer reply wakeups contending for the owner GIL);
    # deeper pipelining (4) measured WORSE — more pusher-thread churn
    GROUP_SIZE = 64

    def _pop_group(self, key: tuple, limit: int) -> list:
        with self._lock:
            q = self._queues.get(key)
            if not q:
                return []
            # fair-share grouping: one pusher must not swallow the whole
            # queue while sibling LEASES sit idle — divide by pushers that
            # actually HOLD a worker (probers parked at a saturated raylet
            # would otherwise shrink groups to 1 and turn every task into
            # its own round trip)
            share = max(1, len(q) // max(1, self._holding.get(key, 1)))
            take = min(limit, share)
            out = []
            while q and len(out) < take:
                out.append(q.popleft())
            return out

    def _note_acquired(self, key: tuple):
        """A pusher acquired a lease: count it as a holder, and — grant-
        driven growth — spawn the NEXT prober while queued work outruns
        the pool, so the pool ramps to cluster capacity one grant at a
        time with at most one prober parked at a saturated raylet."""
        spawn = False
        with self._lock:
            self._holding[key] = self._holding.get(key, 0) + 1
            q = self._queues.get(key)
            if q and self._pushers.get(key, 0) < self._max_per_shape:
                self._pushers[key] = self._pushers.get(key, 0) + 1
                spawn = True
        if spawn:
            threading.Thread(target=self._pusher, args=(key,),
                             name="ray_tpu-lease-pusher", daemon=True).start()

    def _note_released(self, key: tuple):
        with self._lock:
            left = self._holding.get(key, 0) - 1
            if left > 0:
                self._holding[key] = left
            else:
                self._holding.pop(key, None)

    def _pusher(self, key: tuple):
        lease: Lease | None = None
        window: deque = deque()   # (tasks, PendingCall) in push order

        def _drop_in_flight(tasks):
            with self._lock:
                for t in tasks:
                    self._in_flight.pop(t.get("task_id", ""), None)

        def _break_all(error, info=None):
            # the lease died: every group in the window is lost together —
            # ONE death-info query covers them all
            nonlocal lease
            broken, lease = lease, None
            self._note_released(key)
            if info is None:
                info = self._death_info(broken)
            try:
                broken.close()
            except Exception:  # noqa: BLE001
                pass
            while window:
                tasks, _, _ = window.popleft()
                _drop_in_flight(tasks)
                for t in tasks:
                    self._handle_break(t, error, info)

        def _send_group(tasks) -> bool:
            with self._lock:
                for t in tasks:
                    self._in_flight[t.get("task_id", "")] = (t, lease)
            t_send = time.perf_counter()
            try:
                if len(tasks) == 1:
                    pending = lease.client.call_async("push_task",
                                                      task=tasks[0])
                else:
                    pending = lease.client.call_async("push_tasks",
                                                      tasks=tasks)
            except (ConnectionLost, OSError) as e:
                window.append((tasks, None, t_send))
                _break_all(e)
                return False
            window.append((tasks, pending, t_send))
            return True

        try:
            while not self._stopping:
                # fill the window: send up to PIPELINE_DEPTH task GROUPS
                # (GROUP_SIZE tasks per RPC) before waiting on the oldest
                # reply — groups amortize the framing/pickle overhead,
                # pipelining hides the owner's round trip (the worker
                # executes its connection's requests in order)
                while lease is not None and len(window) < self.PIPELINE_DEPTH:
                    tasks = self._pop_group(key, self.GROUP_SIZE)
                    tasks = [t for t in tasks if not t.get("cancelled")]
                    if not tasks:
                        break
                    if not _send_group(tasks):
                        break
                if window:
                    tasks, pending, t_send = window.popleft()
                    try:
                        if pending is None:
                            raise ConnectionLost("lease lost before send")
                        reply = pending.result(timeout=None)
                        if _metrics.enabled():
                            _h_push_rtt.observe(
                                time.perf_counter() - t_send)
                        results = (reply or {}).get("results")
                        if results and self._on_direct_results:
                            # small returns came back IN the reply:
                            # land them in the owner's store before the
                            # tasks are considered complete
                            self._on_direct_results(results)
                        # lineage marker: these objects EXISTED (the node
                        # may still die before the batched location flush
                        # — recovery then resubmits with no lease channel
                        # left to watch)
                        for t in tasks:
                            t["_completed"] = True
                        _drop_in_flight(tasks)
                    except (ConnectionLost, OSError, TimeoutError,
                            EOFError) as e:
                        _drop_in_flight(tasks)
                        info = self._death_info(lease) if lease else {}
                        for t in tasks:
                            self._handle_break(t, e, info)
                        if lease is not None:
                            _break_all(e, info)
                    continue
                # window empty: need a lease and/or more work
                task = self._pop(key)
                if task is None:
                    return
                tid = task.get("task_id", "")
                # visible to cancel() from pop to completion — with
                # lease=None while still acquiring ("queued" semantics)
                with self._lock:
                    self._in_flight[tid] = (task, None)
                if lease is None:
                    t_acq = time.perf_counter()
                    lease = self._acquire_lease(task)
                    if lease is not None:
                        if _metrics.enabled():
                            _h_acquire.observe(time.perf_counter() - t_acq)
                        self._note_acquired(key)
                if lease is None:
                    # unplaceable via lease (infeasible / exhausted
                    # retries): the raylet queue owns parking, autoscaler
                    # demand reporting and the infeasible error path
                    _drop_in_flight([task])
                    if not self._stopping and not task.get("cancelled"):
                        try:
                            self._legacy_submit(task)
                        except Exception:  # noqa: BLE001
                            pass  # raylet gone; owner is shutting down
                    continue
                if task.get("cancelled"):
                    _drop_in_flight([task])
                    continue
                _send_group([task])
        finally:
            if lease is not None:
                lease.close()
                self._note_released(key)
            with self._lock:
                left = self._pushers.get(key, 1) - 1
                if left <= 0:
                    self._pushers.pop(key, None)
                else:
                    self._pushers[key] = left

    def _acquire_lease(self, task: dict) -> Lease | None:
        """Request a lease from the local raylet, following spillback
        redirects; parks (server-side, event-driven) while the cluster is
        saturated.

        Every connection here is this pusher's OWN: the RPC server
        handles a connection's requests serially, so a parked lease
        request on the shared driver↔raylet client would stall every
        other driver RPC (gets, reports, cancels) behind it.
        """
        home: RpcClient | None = None
        transient: RpcClient | None = None
        # One idempotency token per logical acquisition, held across
        # transport retries: a grant whose reply was lost (reset,
        # healed partition) is returned AGAIN by the raylet instead of
        # leasing a second worker — without it every lost reply leaked a
        # granted worker until the never-dialed watchdog reclaimed it.
        import uuid as _uuid
        token = _uuid.uuid4().hex
        transport_failures = 0
        try:
            try:
                home = RpcClient(self._raylet.address, label="driver")
            except OSError:
                return None
            target = home
            hops = 0
            retries = 0
            while not self._stopping:
                try:
                    resp = target.call(
                        "request_lease",
                        demand=task.get("resources", {}),
                        runtime_env=task.get("runtime_env"),
                        timeout_s=self._lease_block_s,
                        spill_count=hops,
                        token=token,
                        timeout=self._lease_block_s + 5.0)
                except (ConnectionLost, OSError, TimeoutError, EOFError):
                    transport_failures += 1
                    if self._stopping or transport_failures > 2:
                        return None  # raylet unreachable: legacy fallback
                    # the request may have been APPLIED with the reply
                    # lost: redial and retry with the SAME token so an
                    # already-granted worker is reused, not duplicated
                    time.sleep(0.2)
                    if transient is not None:
                        transient.close()
                        transient = None
                    home.close()
                    try:
                        home = RpcClient(self._raylet.address,
                                         label="driver")
                    except OSError:
                        return None
                    target = home
                    hops = 0
                    continue
                if resp.get("ok"):
                    try:
                        return Lease(resp["worker_addr"], resp["worker_id"],
                                     resp["node_id"], target.address)
                    except (OSError, ConnectionLost):
                        # ConnectionLost (not an OSError): the attach
                        # call_async can raise it when the worker died
                        # between grant and dial-completion — same
                        # handback as a failed dial
                        # dial failed (worker died, or owner-side fd
                        # pressure): hand the grant BACK — an undailed
                        # lease would leak the worker + its resources
                        try:
                            target.call("lease_closed",
                                        worker_id=resp["worker_id"],
                                        timeout=5)
                        except Exception:  # noqa: BLE001
                            pass
                        return None
                if resp.get("redirect") and hops < 4:
                    hops += 1
                    if transient is not None:
                        transient.close()
                        transient = None
                    try:
                        transient = RpcClient(tuple(resp["redirect"]),
                                              label="driver")
                    except OSError:
                        return None
                    target = transient
                    continue
                if resp.get("retry"):
                    # parked past the server-side window: KEEP WAITING —
                    # a feasible-but-busy cluster eventually grants, and
                    # falling back to the raylet-queue path here pushed
                    # entire floods through the non-direct-return channel
                    # (200k-task drains then crawled through cross-node
                    # pulls of tiny results). The generous cap only
                    # breaks true wedges; the task then takes the legacy
                    # path's recovery machinery.
                    retries += 1
                    if retries % 3 == 0 and target is not home:
                        # go home: the local raylet parks in ITS queue
                        if transient is not None:
                            transient.close()
                            transient = None
                        target = home
                        hops = 0
                    if retries >= 240:
                        return None
                    continue
                return None  # infeasible or unknown reply
            return None
        finally:
            if transient is not None:
                transient.close()
            if home is not None:
                home.close()

    def _death_info(self, lease: Lease) -> dict:
        client = None
        try:
            client = RpcClient(lease.raylet_addr, timeout=5,
                               label="driver")
            info = client.call("worker_death_info",
                               worker_id=lease.worker_id) or {}
            info.setdefault("node_id", lease.node_id)
            info.setdefault("worker_id", lease.worker_id)
            return info
        except Exception:  # noqa: BLE001 - node died with the worker
            return {"node_unreachable": True, "node_id": lease.node_id,
                    "worker_id": lease.worker_id}
        finally:
            if client is not None:
                client.close()

    def _handle_break(self, task: dict, error: BaseException,
                      death_info: dict):
        if self._stopping:
            return  # owner shutting down; the store may be unmapped
        if task.get("cancelled"):
            return  # force-cancel killed the worker; error pre-stored
        if task.get("_completed"):
            return  # its push already completed (window break after it)
        if death_info.get("oom_killed"):
            # memory-pressure kill: separate budget + backoff (the node is
            # likely still pressured), never burning max_retries
            from ray_tpu.utils import exceptions as exc
            from ray_tpu.utils.config import get_config

            total = get_config().task_oom_retries
            left = task.get("_oom_retries_left", total)
            if left > 0:
                task["_oom_retries_left"] = left - 1
                time.sleep(min(8.0, 1.0 * 2 ** (total - left)))
                self.submit(task)
            else:
                self._on_task_failed(task, exc.OutOfMemoryError(
                    f"task {task.get('name')}: worker killed to relieve "
                    f"host memory pressure ({total} OOM retries "
                    f"exhausted)"))
            return
        if task.get("max_retries", 0) > 0:
            task["max_retries"] -= 1
            self.submit(task)
        else:
            self._on_task_failed(
                task, _typed_death_error(task, error, death_info))

    # ------------------------------------------------------------------

    def cancel(self, oids: set, force: bool = False):
        """Cancel a lease-managed task by return oid. Returns
        ('queued', task) — removed before it was pushed, caller seals the
        cancel error — or ('running', task) — the executing node's raylet
        was told to interrupt/kill the leased worker — or None."""
        with self._lock:
            for q in self._queues.values():
                for i, t in enumerate(q):
                    if oids & set(t.get("return_oids", ())):
                        t["cancelled"] = True
                        del q[i]
                        return ("queued", t)
            hit = None
            for task, lease in self._in_flight.values():
                if oids & set(task.get("return_oids", ())):
                    hit = (task, lease)
                    break
        if hit is None:
            return None
        task, lease = hit
        task["cancelled"] = True
        if lease is None:
            # its pusher is still acquiring a lease; the flag makes it
            # skip the push — caller seals the cancel error
            return ("queued", task)
        client = None
        try:
            client = RpcClient(lease.raylet_addr, timeout=10,
                               label="driver")
            client.call("cancel_leased", worker_id=lease.worker_id,
                        task=task, force=force)
        except (ConnectionLost, OSError, TimeoutError):
            pass  # node dying anyway; the lease break seals the outcome
        finally:
            if client is not None:
                client.close()
        return ("running", task)


def _typed_death_error(task: dict, error: BaseException,
                       death_info: dict) -> BaseException:
    """Death-boundary error taxonomy: a crashed peer surfaces as
    NodeDiedError / WorkerCrashedError (carrying node/worker identity
    and the injected crash point when there is one), never a bare
    transport ConnectionLost/TimeoutError whose redial deadline happens
    to be the thing that fired."""
    from ray_tpu.utils import exceptions as exc

    if isinstance(error, exc.RayTpuError):
        return error
    name = task.get("name", "?")
    if death_info.get("node_unreachable"):
        return exc.NodeDiedError(
            death_info.get("node_id"),
            f"raylet unreachable while task {name!r} was leased there "
            f"({error!r})")
    reason = f"worker died while running task {name!r}"
    if death_info.get("crash_point"):
        reason += f" at crash point {death_info['crash_point']}"
    if death_info.get("last_words"):
        last = " | ".join(death_info["last_words"][-2:])
        reason += f"; last words: {last}"
    return exc.WorkerCrashedError(f"{reason} ({error!r})")
