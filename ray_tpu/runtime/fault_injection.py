"""Deterministic, config-driven fault-injection plane for the RPC layer.

Reference analog: the reference's chaos tooling (``python/ray/tests/chaos``
+ the gRPC fault-injection knobs its networking tests lean on). Every
transport primitive in ``runtime/rpc.py`` (``RpcServer``, ``RpcClient``,
``ReconnectingRpcClient``, ``PushSubscriber``) consults the process-global
``plane`` on connect, send, and receive; with no plan loaded the consult
is a single attribute read (``plane.active`` is False).

A *plan* is a dict::

    {"version": 3,              # monotonically increasing; replays ignored
     "seed": 42,                # base seed for probabilistic rules
     "endpoints": {"gcs": ["127.0.0.1:6379"]},   # name -> address list
     "rules": [
        {"id": "cut-gcs", "fault": "partition",
         "src": "driver", "dst": "gcs", "direction": "both"},
        {"fault": "duplicate", "method": "request_lease",
         "src": "raylet", "direction": "recv", "max_hits": 1},
     ]}

Rule fields (all optional except ``fault``):

- ``fault``: ``drop`` | ``delay`` | ``duplicate`` | ``reset`` |
  ``partition`` | ``crash``. ``partition`` severs matching live channels
  AND refuses new connections until the rule is removed (healed); the
  other message faults act per message. ``crash`` is a PROCESS fault:
  it never matches message traffic and instead fires at named *crash
  points* registered throughout the runtime (``maybe_crash("gcs.
  after_wal_append")``) — on the nth seeded hit the host process writes
  a last-words marker line to raw stderr (the log plane's ``.err``
  redirect keeps it; supervisors harvest it) and dies via ``os._exit``
  (or SIGKILL with ``signal: "kill"``).
- ``src``: the LOCAL endpoint label of the channel (clients are labeled
  at construction — ``driver``, ``owner``, ``raylet``, ``worker``;
  servers consult with their ``fault_label``). ``*``/absent matches any.
- ``dst``: peer address as ``host:port``, an endpoint NAME resolved
  through the plan's ``endpoints`` map, or ``*``.
- ``direction``: ``send`` | ``recv`` | ``both`` (one-way faults).
- ``method``: RPC method name, or ``*``.
- ``point`` (``crash`` rules): crash-point name or fnmatch pattern
  (``worker.*``). The catalog lives in docs/crash_chaos.md.
- ``proc`` (``crash`` rules): process role the rule may kill —
  ``gcs`` | ``raylet`` | ``worker`` | ``driver`` | ``*``. Every entry
  point stamps its role on the plane (:func:`set_process_label`); the
  driver-hosted in-process GCS/head raylet keep the ``driver`` label,
  so a ``proc: "raylet"`` rule can only ever kill an external raylet,
  never the test/driver process.
- ``nth`` (fire only on the nth matching call), ``every`` (every nth),
  ``p`` (seeded probability), ``max_hits`` (stop after N injections).
  Counters are per process: a ``crash`` rule with ``nth: 1`` kills each
  matching process at its next hit of the point.
- ``delay_s``: sleep for ``delay`` faults (default 0.05).
- ``signal`` (``crash`` rules): ``exit`` (default, ``os._exit(137)``)
  or ``kill`` (``SIGKILL`` to self — no atexit, no buffered flush
  beyond the already-written marker).

Runtime switching: plans live under the GCS KV key
(``__fault_injection__`` / ``plan``) — the GCS applies writes to its own
process immediately (``rpc_kv_put``), and every other enabled process
polls through :func:`start_kv_watcher`, so a test can open and heal a
partition mid-workload with one ``kv_put``. The watcher's own channel
uses :data:`FAULT_CONTROL_LABEL` and is exempt from injection (a plane
that could partition its own control channel could never heal).

Config flags (``ray_tpu/utils/config.py``, env ``RAY_TPU_FAULT_*``):
``fault_injection_enabled``, ``fault_injection_seed``,
``fault_injection_plan`` (inline JSON or ``@/path/to/plan.json``),
``fault_injection_kv_poll_s``.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
from typing import Any

# KV coordinates of the live plan (see GcsServer.rpc_kv_put).
KV_NS = "__fault_injection__"
KV_KEY = "plan"

# Channels carrying fault-plan control traffic are never injected.
FAULT_CONTROL_LABEL = "fault-control"

PASS = "pass"
DROP = "drop"
DELAY = "delay"
DUPLICATE = "duplicate"
RESET = "reset"
PARTITION = "partition"
# Consumed by runtime/prestart.py only (consulted with method
# "fork_worker"): SIGKILL the zygote template right before a fork is
# requested from it, proving the cold-spawn fallback. The RPC layer
# treats it as PASS (it only acts on DROP/DUPLICATE/RESET), so rules
# should pin ``method: "fork_worker"`` to avoid burning hit budgets on
# unrelated messages.
KILL_TEMPLATE = "kill_template"
# Process-crash rule kind: fires at named maybe_crash() points, not on
# message traffic (consult/check_connect skip it entirely).
CRASH = "crash"

_FAULTS = (DROP, DELAY, DUPLICATE, RESET, PARTITION, KILL_TEMPLATE,
           CRASH)

# Last-words marker written to raw fd 2 right before an injected death.
# The worker/raylet ``.err`` redirect keeps it even through SIGKILL;
# supervisors and the log plane key off this prefix (see
# log_plane.CRASH_MARKER ingestion and worker_pool last-words harvest).
CRASH_MARKER = "RAY_TPU_CRASH"


class InjectedConnectionReset(OSError):
    """Raised on connect into an injected partition (an OSError so every
    existing dial-failure path treats it as an unreachable peer)."""


class _Rule:
    __slots__ = ("rid", "fault", "src", "dst", "direction", "method",
                 "nth", "every", "p", "max_hits", "delay_s",
                 "point", "proc", "signal", "calls", "hits", "rng")

    def __init__(self, spec: dict, index: int, seed: int):
        fault = spec.get("fault")
        if fault not in _FAULTS:
            raise ValueError(f"unknown fault {fault!r} (rule {index})")
        self.rid = str(spec.get("id", f"rule{index}:{fault}"))
        self.fault = fault
        self.src = spec.get("src", "*")
        self.dst = spec.get("dst", "*")
        self.direction = spec.get("direction", "both")
        self.method = spec.get("method", "*")
        self.nth = spec.get("nth")
        self.every = spec.get("every")
        self.p = spec.get("p")
        self.max_hits = spec.get("max_hits")
        self.delay_s = float(spec.get("delay_s", 0.05))
        # crash-rule fields (ignored by message faults)
        self.point = spec.get("point", "*")
        self.proc = spec.get("proc", "*")
        self.signal = spec.get("signal", "exit")
        self.calls = 0
        self.hits = 0
        # per-rule seeded stream: decisions replay exactly for a given
        # (plan seed, rule position, rule id) regardless of other rules
        self.rng = random.Random(f"{seed}:{index}:{self.rid}")

    def matches_point(self, point: str, proc_label: str | None) -> bool:
        if self.proc != "*" and self.proc != proc_label:
            return False
        if self.point == "*" or self.point == point:
            return True
        return fnmatch.fnmatchcase(point, self.point)

    def matches(self, label: str | None, direction: str, peer_key: str,
                method: str | None, endpoints: dict) -> bool:
        if self.src != "*" and self.src != label:
            return False
        if self.direction != "both" and self.direction != direction:
            return False
        if self.method != "*" and self.method != method:
            return False
        if self.dst != "*":
            targets = endpoints.get(self.dst)
            if targets is None:
                targets = (self.dst,)
            if peer_key not in targets:
                return False
        return True

    def fires(self) -> bool:
        """Scheduling predicate; caller holds the plane lock."""
        self.calls += 1
        if self.max_hits is not None and self.hits >= self.max_hits:
            return False
        if self.nth is not None:
            fire = self.calls == self.nth
        elif self.every is not None:
            fire = self.calls % self.every == 0
        elif self.p is not None:
            fire = self.rng.random() < self.p
        else:
            fire = True
        if fire:
            self.hits += 1
        return fire


def _peer_key(peer) -> str:
    if isinstance(peer, str):
        return peer
    try:
        return f"{peer[0]}:{peer[1]}"
    except (TypeError, IndexError):
        return str(peer)


class FaultPlane:
    """Process-global rule engine. ``active`` is the hot-path gate: the
    RPC layer reads it before building any consult arguments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: tuple[_Rule, ...] = ()
        self._endpoints: dict[str, tuple[str, ...]] = {}
        self._seed = 0
        self.version = -1
        self.active = False
        self.stats: dict[str, int] = {}
        # role stamp consulted by crash rules' ``proc`` scoping; set
        # once per process by set_process_label() at the entry point
        self.process_label: str | None = None
        # test seam: a harness may intercept the injected death instead
        # of losing its own process (in-process GCS chaos tests)
        self._crash_handler = None

    # -- plan management ------------------------------------------------

    def set_seed(self, seed: int):
        with self._lock:
            self._seed = int(seed)

    def load_plan(self, plan: dict | None):
        """Install a plan atomically (None or empty rules = heal all)."""
        plan = plan or {}
        rules = plan.get("rules") or []
        with self._lock:
            seed = int(plan.get("seed", self._seed))
            self._seed = seed
            self._endpoints = {
                name: tuple(addrs) if isinstance(addrs, (list, tuple))
                else (addrs,)
                for name, addrs in (plan.get("endpoints") or {}).items()}
            self._rules = tuple(_Rule(spec, i, seed)
                                for i, spec in enumerate(rules))
            if "version" in plan:
                self.version = int(plan["version"])
            self.active = bool(self._rules)

    def clear(self):
        with self._lock:
            self._rules = ()
            self._endpoints = {}
            self.active = False
            self.stats = {}

    # -- consult points -------------------------------------------------

    def check_connect(self, label: str | None, peer):
        """Gate for new outbound connections: raises into an open
        partition (direction ``both``/``send`` — a one-way inbound
        partition still lets this side dial)."""
        if label == FAULT_CONTROL_LABEL:
            return
        peer_key = _peer_key(peer)
        with self._lock:
            for rule in self._rules:
                if rule.fault != PARTITION:
                    continue
                if rule.direction == "recv":
                    continue
                if rule.matches(label, "send", peer_key, None,
                                self._endpoints):
                    self._count(rule)
                    raise InjectedConnectionReset(
                        f"injected partition: {label} -> {peer_key} "
                        f"({rule.rid})")

    def consult(self, label: str | None, direction: str, peer,
                method: str | None) -> str:
        """Decide the fate of one message. Returns PASS / DROP /
        DUPLICATE / RESET (PARTITION maps to RESET: the channel is
        severed and redials are refused by check_connect until healed).
        Delay rules sleep inline and keep scanning."""
        if label == FAULT_CONTROL_LABEL:
            return PASS
        peer_key = _peer_key(peer)
        delay = 0.0
        action = PASS
        with self._lock:
            for rule in self._rules:
                if rule.fault == CRASH:
                    continue   # process fault: fires at maybe_crash only
                if not rule.matches(label, direction, peer_key, method,
                                    self._endpoints):
                    continue
                if not rule.fires():
                    continue
                self._count(rule)
                if rule.fault == DELAY:
                    delay += rule.delay_s
                    continue
                action = RESET if rule.fault == PARTITION else rule.fault
                break
        if delay:
            time.sleep(delay)
        return action

    def maybe_crash(self, point: str):
        """Named crash point. A no-op (one attribute read) unless a plan
        with a matching ``crash`` rule is loaded; on the nth seeded hit
        the process writes a last-words marker to raw fd 2 and dies.
        Registered points form the catalog in docs/crash_chaos.md —
        ``gcs.after_wal_append``, ``raylet.before_lease_grant``,
        ``worker.mid_task``, ``replica.mid_decode``, ...
        """
        if not self.active:
            return
        fired = None
        with self._lock:
            for rule in self._rules:
                if rule.fault != CRASH:
                    continue
                if not rule.matches_point(point, self.process_label):
                    continue
                if not rule.fires():
                    continue
                self._count(rule)
                fired = rule
                break
        if fired is None:
            return
        self._die(point, fired)

    def _die(self, point: str, rule: _Rule):
        """Injected death: marker first (raw fd 2 — survives SIGKILL
        because it is already in the .err redirect by the time we die),
        then exit without any cleanup, exactly like a real crash."""
        marker = (f"{CRASH_MARKER} point={point} rule={rule.rid} "
                  f"pid={os.getpid()} "
                  f"proc={self.process_label or '?'}\n")
        try:
            os.write(2, marker.encode())
        except OSError:
            pass
        if self._crash_handler is not None:
            self._crash_handler(point, rule)
            return
        if rule.signal == "kill":
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGKILL)
            time.sleep(60)   # pending-signal window; never returns
        os._exit(137)

    def set_crash_handler(self, fn):
        """Test seam: ``fn(point, rule)`` replaces the injected death
        (None restores real semantics). In-process chaos tests use this
        to crash an embedded server without losing the host process."""
        self._crash_handler = fn

    def _count(self, rule: _Rule):
        self.stats[rule.rid] = self.stats.get(rule.rid, 0) + 1


plane = FaultPlane()


def set_process_label(label: str):
    """Stamp this process's role (``gcs``/``raylet``/``worker``/
    ``driver``) for crash rules' ``proc`` scoping. Entry points call it
    unconditionally — it is one attribute write and must happen even
    when injection is disabled, so a plan enabled later via env in a
    child finds the label in place."""
    plane.process_label = label


def maybe_crash(point: str):
    """Module-level convenience for the process-global plane."""
    plane.maybe_crash(point)


# ----------------------------------------------------------------------
# plan transport (GCS KV)
# ----------------------------------------------------------------------

def decode_plan(value: Any) -> dict | None:
    """KV values may arrive as a dict (python clients) or JSON text."""
    if value is None:
        return None
    if isinstance(value, (bytes, bytearray)):
        value = value.decode()
    if isinstance(value, str):
        value = json.loads(value)
    if not isinstance(value, dict):
        raise ValueError(f"fault plan must be a dict, got {type(value)}")
    return value


def put_plan(gcs_address, plan: dict):
    """Write a plan to the GCS KV switch key over an injection-exempt
    channel (tests open/heal partitions with this while one is open)."""
    from ray_tpu.runtime.rpc import RpcClient

    client = RpcClient(tuple(gcs_address), timeout=10,
                       label=FAULT_CONTROL_LABEL)
    try:
        client.call("kv_put", ns=KV_NS, key=KV_KEY, value=plan, timeout=10)
    finally:
        client.close()


_watcher_lock = threading.Lock()
_watcher_stop: threading.Event | None = None


def start_kv_watcher(gcs_address, poll_s: float = 0.25):
    """Poll the GCS KV plan key and apply version changes to the local
    plane. Idempotent per process; the channel is injection-exempt."""
    global _watcher_stop
    with _watcher_lock:
        if _watcher_stop is not None:
            return
        _watcher_stop = threading.Event()
        stop = _watcher_stop
    address = tuple(gcs_address)

    def _loop():
        from ray_tpu.runtime.rpc import RpcClient

        client = None
        while not stop.wait(poll_s):
            try:
                if client is None:
                    client = RpcClient(address, timeout=5,
                                       label=FAULT_CONTROL_LABEL)
                raw = client.call("kv_get", ns=KV_NS, key=KV_KEY,
                                  timeout=5)
                plan = decode_plan(raw)
                if plan is not None and \
                        int(plan.get("version", 0)) != plane.version:
                    plane.load_plan(plan)
            except Exception:  # noqa: BLE001 - GCS busy/down: redial next
                if client is not None:
                    client.close()
                    client = None
        if client is not None:
            client.close()

    threading.Thread(target=_loop, daemon=True,
                     name="fault-kv-watcher").start()


def stop_kv_watcher():
    global _watcher_stop
    with _watcher_lock:
        if _watcher_stop is not None:
            _watcher_stop.set()
            _watcher_stop = None


def reset_after_fork():
    """Called in a zygote-forked child before any worker code runs: the
    child must start with a FRESH plane (no rules, version -1) and no
    watcher bookkeeping — a template never loads a plan or starts the
    watcher, but the child enforces the invariant rather than assuming
    it. The worker's own ``maybe_init_from_config`` then rebuilds state
    from ITS environment, exactly like a cold-spawned worker."""
    global plane, _watcher_stop
    with _watcher_lock:
        _watcher_stop = None   # watcher threads do not survive fork
    plane = FaultPlane()


def maybe_init_from_config(gcs_address=None, process_label=None):
    """Called by every process entry point (driver runtime, raylet, GCS,
    worker). The role stamp is applied unconditionally; everything else
    is a no-op unless ``RAY_TPU_FAULT_INJECTION_ENABLED`` is set — the
    disabled path costs one config read at startup, nothing per
    message."""
    from ray_tpu.utils.config import get_config

    if process_label is not None:
        set_process_label(process_label)
    cfg = get_config()
    if not cfg.fault_injection_enabled:
        return
    plane.set_seed(cfg.fault_injection_seed)
    raw = cfg.fault_injection_plan
    if raw:
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        plane.load_plan(json.loads(raw))
    if gcs_address is not None:
        start_kv_watcher(tuple(gcs_address), cfg.fault_injection_kv_poll_s)
