"""Cross-language (non-pickle) wire format: msgpack.

Reference analog: the C++/Java clients serialize task args and returns
with msgpack (``bazel/ray_deps_setup.bzl:304`` pulls msgpack for exactly
this; cross-language calls use function DESCRIPTORS, not pickled
closures). This module is a dependency-free msgpack subset codec —
enough for the cross-language value domain:

    nil, bool, int64, float64, str, bin, array, map(str->value)

Python objects outside that domain fail loudly (the cross-language
contract is plain data, like the reference's).

Also defines the function-descriptor convention: a C++/external client
submits ``{"function_ref": "pkg.module:qualname"}`` and the executing
Python worker resolves it by import — never by unpickling code.
"""

from __future__ import annotations

import struct


class XlangEncodeError(TypeError):
    pass


def dumps(obj) -> bytes:
    out = bytearray()
    _pack(obj, out)
    return bytes(out)


def _pack(obj, out: bytearray):
    if obj is None:
        out.append(0xC0)
    elif obj is True:
        out.append(0xC3)
    elif obj is False:
        out.append(0xC2)
    elif isinstance(obj, int):
        if 0 <= obj <= 0x7F:
            out.append(obj)
        elif -32 <= obj < 0:
            out.append(0x100 + obj)
        elif -(1 << 63) <= obj < (1 << 64):
            if obj >= 0:
                out.append(0xCF)
                out += struct.pack(">Q", obj)
            else:
                out.append(0xD3)
                out += struct.pack(">q", obj)
        else:
            raise XlangEncodeError(f"int out of 64-bit range: {obj}")
    elif isinstance(obj, float):
        out.append(0xCB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        n = len(b)
        if n <= 31:
            out.append(0xA0 | n)
        elif n <= 0xFF:
            out += bytes((0xD9, n))
        elif n <= 0xFFFF:
            out.append(0xDA)
            out += struct.pack(">H", n)
        else:
            out.append(0xDB)
            out += struct.pack(">I", n)
        out += b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        n = len(b)
        if n <= 0xFF:
            out += bytes((0xC4, n))
        elif n <= 0xFFFF:
            out.append(0xC5)
            out += struct.pack(">H", n)
        else:
            out.append(0xC6)
            out += struct.pack(">I", n)
        out += b
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n <= 15:
            out.append(0x90 | n)
        elif n <= 0xFFFF:
            out.append(0xDC)
            out += struct.pack(">H", n)
        else:
            out.append(0xDD)
            out += struct.pack(">I", n)
        for item in obj:
            _pack(item, out)
    elif isinstance(obj, dict):
        n = len(obj)
        if n <= 15:
            out.append(0x80 | n)
        elif n <= 0xFFFF:
            out.append(0xDE)
            out += struct.pack(">H", n)
        else:
            out.append(0xDF)
            out += struct.pack(">I", n)
        for k, v in obj.items():
            _pack(k, out)
            _pack(v, out)
    else:
        raise XlangEncodeError(
            f"type {type(obj).__name__} is outside the cross-language "
            f"value domain (nil/bool/int/float/str/bin/array/map)")


def loads(data: bytes):
    obj, off = _unpack(memoryview(data), 0)
    return obj


def _unpack(mv: memoryview, off: int):
    b = mv[off]
    off += 1
    if b <= 0x7F:
        return b, off
    if b >= 0xE0:
        return b - 0x100, off
    if 0x80 <= b <= 0x8F:
        return _unpack_map(mv, off, b & 0x0F)
    if 0x90 <= b <= 0x9F:
        return _unpack_array(mv, off, b & 0x0F)
    if 0xA0 <= b <= 0xBF:
        n = b & 0x1F
        return str(mv[off:off + n], "utf-8"), off + n
    if b == 0xC0:
        return None, off
    if b == 0xC2:
        return False, off
    if b == 0xC3:
        return True, off
    if b == 0xC4:
        n = mv[off]
        return bytes(mv[off + 1:off + 1 + n]), off + 1 + n
    if b == 0xC5:
        (n,) = struct.unpack_from(">H", mv, off)
        return bytes(mv[off + 2:off + 2 + n]), off + 2 + n
    if b == 0xC6:
        (n,) = struct.unpack_from(">I", mv, off)
        return bytes(mv[off + 4:off + 4 + n]), off + 4 + n
    if b == 0xCA:
        (v,) = struct.unpack_from(">f", mv, off)
        return v, off + 4
    if b == 0xCB:
        (v,) = struct.unpack_from(">d", mv, off)
        return v, off + 8
    if b == 0xCC:
        return mv[off], off + 1
    if b == 0xCD:
        (v,) = struct.unpack_from(">H", mv, off)
        return v, off + 2
    if b == 0xCE:
        (v,) = struct.unpack_from(">I", mv, off)
        return v, off + 4
    if b == 0xCF:
        (v,) = struct.unpack_from(">Q", mv, off)
        return v, off + 8
    if b == 0xD0:
        (v,) = struct.unpack_from(">b", mv, off)
        return v, off + 1
    if b == 0xD1:
        (v,) = struct.unpack_from(">h", mv, off)
        return v, off + 2
    if b == 0xD2:
        (v,) = struct.unpack_from(">i", mv, off)
        return v, off + 4
    if b == 0xD3:
        (v,) = struct.unpack_from(">q", mv, off)
        return v, off + 8
    if b == 0xD9:
        n = mv[off]
        return str(mv[off + 1:off + 1 + n], "utf-8"), off + 1 + n
    if b == 0xDA:
        (n,) = struct.unpack_from(">H", mv, off)
        return str(mv[off + 2:off + 2 + n], "utf-8"), off + 2 + n
    if b == 0xDB:
        (n,) = struct.unpack_from(">I", mv, off)
        return str(mv[off + 4:off + 4 + n], "utf-8"), off + 4 + n
    if b == 0xDC:
        (n,) = struct.unpack_from(">H", mv, off)
        return _unpack_array(mv, off + 2, n)
    if b == 0xDD:
        (n,) = struct.unpack_from(">I", mv, off)
        return _unpack_array(mv, off + 4, n)
    if b == 0xDE:
        (n,) = struct.unpack_from(">H", mv, off)
        return _unpack_map(mv, off + 2, n)
    if b == 0xDF:
        (n,) = struct.unpack_from(">I", mv, off)
        return _unpack_map(mv, off + 4, n)
    raise ValueError(f"unsupported msgpack byte 0x{b:02x}")


def _unpack_array(mv, off, n):
    out = []
    for _ in range(n):
        item, off = _unpack(mv, off)
        out.append(item)
    return out, off


def _unpack_map(mv, off, n):
    out = {}
    for _ in range(n):
        k, off = _unpack(mv, off)
        v, off = _unpack(mv, off)
        out[k] = v
    return out, off


def resolve_function_ref(ref: str):
    """Import ``pkg.module:qualname`` (reference: cross-language function
    descriptors resolve by name on the executing side)."""
    import importlib

    module_name, sep, qualname = ref.partition(":")
    if not sep:
        raise ValueError(
            f"function_ref must be 'module:qualname', got {ref!r}")
    obj = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    # unwrap @ray_tpu.remote decoration so a shared module works for both
    # Python and external callers
    return getattr(obj, "underlying_function", obj)
