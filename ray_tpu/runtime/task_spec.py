"""Task/actor specifications — the unit of scheduling currency.

Analog of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h:244``): everything the scheduler and an
executing worker need, in one serializable record. Resource demands follow the
reference's model (named float resources: "CPU", "TPU", "memory", custom),
with TPU slice topology as a first-class label.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from ray_tpu.utils.ids import ActorID, ObjectID, PlacementGroupID, TaskID


class TaskType(Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class ResourceSet:
    """Named float resource demand (reference: ``ResourceSet`` with fixed-point
    arithmetic; floats suffice here since demands come from user options)."""

    resources: dict[str, float] = field(default_factory=dict)

    @staticmethod
    def from_options(num_cpus=None, num_tpus=None, memory=None, resources=None):
        r: dict[str, float] = {}
        if num_cpus is not None:
            r["CPU"] = float(num_cpus)
        if num_tpus is not None:
            r["TPU"] = float(num_tpus)
        if memory is not None:
            r["memory"] = float(memory)
        if resources:
            r.update({k: float(v) for k, v in resources.items()})
        return ResourceSet(r)

    def fits_in(self, available: dict[str, float]) -> bool:
        return all(available.get(k, 0.0) >= v - 1e-9 for k, v in self.resources.items())

    def is_empty(self) -> bool:
        return not self.resources or all(v == 0 for v in self.resources.values())


@dataclass
class SchedulingStrategy:
    """Placement policy for one task (reference:
    ``util/scheduling_strategies.py``): DEFAULT (hybrid), SPREAD, node
    affinity, or placement-group bundle affinity."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Any = None
    soft: bool = False
    placement_group_id: PlacementGroupID | None = None
    bundle_index: int = -1


@dataclass
class TaskSpec:
    task_id: TaskID
    task_type: TaskType
    function: Any  # callable or (serialized) function descriptor
    function_name: str
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    return_ids: list[ObjectID] = field(default_factory=list)
    resources: ResourceSet = field(default_factory=ResourceSet)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    # actor fields
    actor_id: ActorID | None = None
    actor_method_name: str | None = None
    sequence_number: int = 0
    max_concurrency: int = 1
    max_restarts: int = 0
    runtime_env: dict | None = None
    # tracing context captured at submission (util/tracing.py); None when
    # tracing is off
    trace_ctx: dict | None = None
    # observability
    submitted_at: float = 0.0
