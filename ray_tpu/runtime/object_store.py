"""In-process object store with waiter notification and reference counting.

Local-mode analog of the reference's two-tier store: the in-process
``CoreWorkerMemoryStore`` (``store_provider/memory_store/memory_store.h``) for
small objects plus plasma for large ones. In local mode a single tier holds
everything; the cluster backend layers a shared-memory tier underneath with
the same interface (put/get/wait/contains/free).

Error values are first-class store entries (as in the reference, where a task
failure stores a ``RayTaskError`` under the return id) so `get` on a failed
task's output raises on every consumer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from ray_tpu.util import metrics as _metrics
from ray_tpu.utils.exceptions import GetTimeoutError, ObjectLostError
from ray_tpu.utils.ids import ObjectID

# Hot-path stage timers, SAMPLED 1-in-64: the in-process store sees
# >100k ops/s, so timing every op would blow the <3% overhead budget
# (tests/test_metrics_plane.py). The mask test runs BEFORE the
# enabled() probe so 63/64 ops pay one int add + one branch; the
# latency distribution stays representative, series counts are ~1/64
# of actual op counts.
_SAMPLE_MASK = 63
_sample = 0
_store_hist = _metrics.histogram(
    "ray_tpu_object_store_s",
    "in-process object store op latency (sampled 1/64)",
    tag_keys=("op",))
_h_put = _store_hist.handle({"op": "put"})
_h_get = _store_hist.handle({"op": "get"})


@dataclass
class _Entry:
    value: Any = None
    is_error: bool = False
    size_bytes: int = 0
    created_at: float = field(default_factory=time.monotonic)


class ObjectStore:
    """Thread-safe object table keyed by ObjectID."""

    def __init__(self, capacity_bytes: int | None = None):
        self._lock = threading.Lock()
        self._objects: dict[ObjectID, _Entry] = {}
        self._cv = threading.Condition(self._lock)
        self._capacity = capacity_bytes
        self._used = 0
        # object id -> number of live references (lineage/ref-count hook)
        self._refcounts: dict[ObjectID, int] = {}
        self._on_free: list[Callable[[ObjectID], None]] = []
        # put-notification subscribers (dependency manager wiring)
        self._on_put: list[Callable[[ObjectID], None]] = []

    def subscribe_put(self, callback: Callable[[ObjectID], None]):
        with self._lock:
            self._on_put.append(callback)

    # --- writes ---

    def put(self, object_id: ObjectID, value: Any, is_error: bool = False,
            size_bytes: int = 0) -> None:
        global _sample
        _sample += 1
        t0 = time.perf_counter() \
            if not (_sample & _SAMPLE_MASK) and _metrics.enabled() else 0.0
        with self._cv:
            if object_id in self._objects:
                return  # objects are immutable; first write wins
            self._objects[object_id] = _Entry(
                value=value, is_error=is_error, size_bytes=size_bytes
            )
            self._used += size_bytes
            self._cv.notify_all()
            callbacks = list(self._on_put)
        for cb in callbacks:
            cb(object_id)
        if t0:
            _h_put.observe(time.perf_counter() - t0)

    # --- reads ---

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get(self, object_ids: list[ObjectID], timeout: float | None = None) -> list[Any]:
        """Block until all ids are present; raise stored errors."""
        global _sample
        _sample += 1
        t0 = time.perf_counter() \
            if not (_sample & _SAMPLE_MASK) and _metrics.enabled() else 0.0
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            for oid in object_ids:
                while oid not in self._objects:
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise GetTimeoutError(
                            f"Timed out waiting for object {oid.hex()}"
                        )
                    self._cv.wait(timeout=remaining)
            results = []
            for oid in object_ids:
                entry = self._objects[oid]
                if entry.is_error:
                    raise entry.value
                results.append(entry.value)
        if t0:
            _h_get.observe(time.perf_counter() - t0)
        return results

    def get_entry(self, object_id: ObjectID):
        """Non-blocking raw fetch: (found, value, is_error)."""
        with self._lock:
            entry = self._objects.get(object_id)
            if entry is None:
                return False, None, False
            return True, entry.value, entry.is_error

    def wait(self, object_ids: list[ObjectID], num_returns: int,
             timeout: float | None = None) -> tuple[list[ObjectID], list[ObjectID]]:
        """Return (ready, not_ready) preserving input order (reference
        ``CoreWorker::Wait`` semantics — ``core_worker.cc:1509``)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                ready = [oid for oid in object_ids if oid in self._objects]
                if len(ready) >= num_returns:
                    break
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            ready_set = set(oid for oid in object_ids if oid in self._objects)
            ready = [oid for oid in object_ids if oid in ready_set][:num_returns]
            taken = set(ready)
            not_ready = [oid for oid in object_ids if oid not in taken]
            return ready, not_ready

    # --- lifecycle ---

    def add_ref(self, object_id: ObjectID, count: int = 1):
        with self._lock:
            self._refcounts[object_id] = self._refcounts.get(object_id, 0) + count

    def remove_ref(self, object_id: ObjectID, count: int = 1):
        free = False
        with self._lock:
            n = self._refcounts.get(object_id, 0) - count
            if n <= 0:
                self._refcounts.pop(object_id, None)
                free = True
            else:
                self._refcounts[object_id] = n
        if free:
            self.free([object_id])

    def free(self, object_ids: Iterable[ObjectID]):
        with self._cv:
            for oid in object_ids:
                entry = self._objects.pop(oid, None)
                if entry is not None:
                    self._used -= entry.size_bytes
        for oid in object_ids:
            for cb in self._on_free:
                cb(oid)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "used_bytes": self._used,
                "capacity_bytes": self._capacity,
            }

    def entries(self, limit: int = 10_000) -> list[dict]:
        """Per-object listing for ``util.state.list_objects`` (local
        mode): same field shape the cluster path answers with, so
        callers never branch on mode. Largest first, capped."""
        import sys as _sys

        now = time.monotonic()
        with self._lock:
            # local mode stores raw values with no recorded payload
            # size; getsizeof at listing time keeps the hot path free
            rows = [(oid.hex(),
                     e.size_bytes or _sys.getsizeof(e.value, 0),
                     e.is_error, now - e.created_at)
                    for oid, e in self._objects.items()]
        rows.sort(key=lambda r: -r[1])
        return [{"object_id": oid, "size_bytes": size,
                 "is_error": err, "age_s": round(age, 3),
                 "locations": ["local"], "state": "in_memory",
                 "holders": [], "pins": 0}
                for oid, size, err, age in rows[:limit]]
