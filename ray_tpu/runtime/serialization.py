"""Object serialization with zero-copy buffer handling.

Analog of the reference's ``python/ray/_private/serialization.py``: cloudpickle
for arbitrary Python objects, with pickle protocol-5 out-of-band buffers so
large numpy/jax host arrays serialize without copying. The (meta, buffers)
split mirrors plasma's metadata/data separation — buffers can be placed in
shared memory by the cluster backend and mapped read-only by consumers.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field

import cloudpickle


@dataclass
class SerializedObject:
    """A serialized value: metadata stream + out-of-band buffers."""

    meta: bytes
    buffers: list = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return len(self.meta) + sum(len(b) for b in self.buffers)


def serialize(value) -> SerializedObject:
    buffers: list = []

    def buffer_callback(buf: pickle.PickleBuffer):
        view = buf.raw()
        buffers.append(view)
        return False  # do not serialize in-band

    stream = io.BytesIO()
    cloudpickle.CloudPickler(stream, protocol=5, buffer_callback=buffer_callback).dump(
        value
    )
    return SerializedObject(meta=stream.getvalue(), buffers=buffers)


def deserialize(obj: SerializedObject):
    return pickle.loads(obj.meta, buffers=[pickle.PickleBuffer(b) for b in obj.buffers])
