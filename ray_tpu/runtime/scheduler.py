"""Per-node task scheduler: the ready queue, the dispatch loop, worker
lease granting, resource accounting, and infeasible-task parking.

Reference analog: ``src/ray/raylet/scheduling/cluster_task_manager.cc``
(queue + spillback + infeasible parking) and ``local_task_manager.cc``
(dispatch to workers), plus the lease queue behind
``NodeManager::HandleRequestWorkerLease`` (node_manager.cc:1778). A
component OWNED by the raylet (``runtime/raylet.py``): placement routing
(``rpc_submit_task``) stays on the raylet — it is the RPC surface and
peer-forwarding concern — and calls ``enqueue`` here once a task is
placed on this node.

One condition variable (``cv``) guards the ready queue, the parked lease
waiters, and the dispatch generation counter; the dispatch loop serves
both queued tasks and lease grants so workers/resources are handed out
by a single arbiter (no lease-vs-task race for the last slot).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from ray_tpu.runtime import fault_injection as _fi
from ray_tpu.runtime.gcs import _fits
from ray_tpu.runtime.rpc import send_msg


class TaskScheduler:
    """Scheduling + resource accounting for one raylet node. ``node`` is
    the owning Raylet (worker pool, GCS client, peer table, error
    paths)."""

    def __init__(self, node, *, resources: dict, infeasible_timeout_s: float):
        self._node = node
        self.total_resources = dict(resources)
        self.available = dict(resources)
        self._res_lock = threading.Lock()
        self.ready: deque[dict] = deque()
        self.cv = threading.Condition()
        # bumped on every completion/registration: the dispatch loop
        # re-checks it under the cv so a kick racing the wait is never lost
        self._dispatch_gen = 0
        # parked worker-lease requests (guarded by cv)
        self.lease_waiters: deque[dict] = deque()
        # cluster-wide infeasible tasks awaiting capacity (autoscaler)
        self.infeasible_timeout_s = infeasible_timeout_s
        self._infeasible: list = []
        self._infeasible_lock = threading.Lock()
        # OOM-backoff timers (cancelled by stop())
        self._deferred_timers: set[threading.Timer] = set()
        self._timers_lock = threading.Lock()
        # idempotency: token -> granted reply, so a retried request_lease
        # (owner redialled after a partition ate the reply) re-reads the
        # grant it already holds instead of burning a second worker
        self._grant_tokens: OrderedDict[str, dict] = OrderedDict()
        self._grant_lock = threading.Lock()
        # set by the raylet: notified on every acquire/release so the
        # versioned resource syncer pushes the new view at RPC latency
        # (reference: ray_syncer RESOURCE_VIEW — runtime/resource_sync.py)
        self.on_resources_changed = lambda: None
        # queue-depth changes feed the same versioned view (placement
        # prefers shallow queues)
        self.on_queue_changed = lambda: None

    def stop(self):
        """Cancel deferred timers and fail parked lease waiters (owners
        fall back instead of blocking out their timeout on a dying
        node). Runs before background threads are joined."""
        with self._timers_lock:
            timers = list(self._deferred_timers)
            self._deferred_timers.clear()
        for timer in timers:
            timer.cancel()
        with self.cv:
            waiters = list(self.lease_waiters)
            self.lease_waiters.clear()
        for waiter in waiters:
            waiter["result"] = {"retry": True}
            waiter["event"].set()

    # ------------------------------------------------------------------
    # queue + kicks
    # ------------------------------------------------------------------

    def enqueue(self, task: dict):
        with self.cv:
            self.ready.append(task)
            self.cv.notify()
        self.on_queue_changed()

    def defer_enqueue(self, task: dict, delay: float):
        """Re-enqueue after a delay (OOM backoff). Timers are tracked so
        stop() cancels them — an untracked timer firing after the store
        closes would enqueue into a dead dispatch loop; the task is then
        lost like any other task queued on a stopping node (cluster-level
        recovery owns that case)."""
        timer = threading.Timer(delay, self._timer_enqueue, args=(task,))
        timer.daemon = True
        with self._timers_lock:
            if self._node._stopping:
                return
            self._deferred_timers.add(timer)
        timer.start()

    def _timer_enqueue(self, task: dict):
        with self._timers_lock:
            self._deferred_timers = {t for t in self._deferred_timers
                                     if t.is_alive()}
        if not self._node._stopping:
            self.enqueue(task)

    def kick(self):
        with self.cv:
            self._dispatch_gen += 1
            self.cv.notify()

    def take_queued_matching(self, matches) -> dict | None:
        """Dequeue (under the cv) the first ready task satisfying
        ``matches`` — the cancel path; the caller stores the error
        OUTSIDE the cv so dispatch/enqueue never stall behind it."""
        with self.cv:
            for i, t in enumerate(self.ready):
                if matches(t):
                    task = t
                    del self.ready[i]
                    self.on_queue_changed()
                    return task
        return None

    def drop_queued_with_env(self, key: str) -> list:
        """Dequeue every ready task whose runtime-env key matches (the
        failed-env fail-fast path); returns the dropped tasks."""
        from ray_tpu.runtime_env import env_key as _env_key

        doomed = []
        with self.cv:
            keep = deque()
            while self.ready:
                task = self.ready.popleft()
                if _env_key(task.get("runtime_env")) == key:
                    doomed.append(task)
                else:
                    keep.append(task)
            self.ready = keep
        if doomed:
            self.on_queue_changed()
        return doomed

    # ------------------------------------------------------------------
    # resource accounting
    # ------------------------------------------------------------------

    def avail_snapshot(self) -> dict:
        with self._res_lock:
            return dict(self.available)

    def try_acquire(self, demand: dict) -> bool:
        with self._res_lock:
            if not _fits(demand, self.available):
                return False
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
        if demand:
            self.on_resources_changed()
        return True

    def release(self, demand: dict):
        if not demand:
            return
        with self._res_lock:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
        self.on_resources_changed()
        # freed capacity may unblock a parked lease request or queued task
        self.kick()

    # ------------------------------------------------------------------
    # dispatch loop (reference: LocalTaskManager::DispatchScheduledTasks)
    # ------------------------------------------------------------------

    def dispatch_loop(self):
        node = self._node
        pool = node.workers
        while not node._stopping:
            with self.cv:
                while (not self.ready and not self.lease_waiters
                       and not node._stopping):
                    self.cv.wait(timeout=0.2)
                if node._stopping:
                    return
                gen0 = self._dispatch_gen
                task = None
                # first task whose resources fit (avoid head-of-line block)
                for i, t in enumerate(self.ready):
                    if _fits(t.get("resources", {}), self.avail_snapshot()):
                        task = t
                        del self.ready[i]
                        break
            if task is not None:
                # dequeues must reach the synced view too, or the GCS
                # `load` only ever rises and placement shuns this node
                self.on_queue_changed()
            self._serve_lease_waiters()
            if task is None:
                # only lease waiters, or no fitting task: block until the
                # next kick (completion/registration/release)
                with self.cv:
                    if self._dispatch_gen == gen0 and not node._stopping:
                        self.cv.wait(timeout=0.1)
                continue
            env_err = pool.bad_env_error(task.get("runtime_env"))
            if env_err is not None:
                from ray_tpu.utils import exceptions as exc
                node._store_task_error(task, exc.RuntimeEnvSetupError(
                    f"runtime env setup failed: {env_err}"))
                continue
            gen = self._dispatch_gen
            worker = pool.idle_worker(task.get("runtime_env"))
            if worker is None:
                self.enqueue(task)
                # wait for a completion/registration kick instead of a
                # fixed sleep: task_done latency, not a poll, sets the
                # dispatch rate when all workers are busy. The generation
                # check under the cv closes the missed-wakeup race (a
                # kick between the snapshot above and this wait).
                with self.cv:
                    if self._dispatch_gen == gen and not node._stopping:
                        self.cv.wait(timeout=0.2)
                continue
            if not self.try_acquire(task.get("resources", {})):
                worker.state = "idle"
                self.enqueue(task)
                continue
            cancelled = False
            with pool.lock:
                # under the lock: cancel_task scans current_task here, and
                # a cancel that ran between the queue pop and this point
                # left a flag on the task dict
                if task.get("cancelled"):
                    cancelled = True
                    worker.state = "idle"
                else:
                    worker.acquired = dict(task.get("resources", {}))
                    worker.current_task = task
                    worker.dispatched_at = time.monotonic()
            if cancelled:
                # outside the workers lock: release kicks the dispatch cv,
                # and holding the worker lock across that inverts the
                # cv→workers lock order used by the lease grant path
                self.release(task.get("resources", {}))
                continue
            try:
                send_msg(worker.conn, {"type": "task", "task": task},
                         worker.send_lock)
            except OSError:
                pool.on_worker_gone(worker)
                self.enqueue(task)

    # ------------------------------------------------------------------
    # worker leases (owner-side lease protocol; reference:
    # NodeManager::HandleRequestWorkerLease node_manager.cc:1778 +
    # CoreWorkerDirectTaskSubmitter direct_task_transport.cc:134,240)
    # ------------------------------------------------------------------

    def request_lease(self, demand: dict, runtime_env: dict | None,
                      timeout_s: float, spill_count: int,
                      token: str | None = None) -> dict:
        """Grant a worker lease: the reply carries the worker's push
        address, and the owner pushes tasks to it directly for as long as
        it holds the lease (= keeps its connection to the worker open).
        Replies: {ok, worker_addr, worker_id, node_id} | {redirect: addr}
        (spillback — caller retries there) | {retry: True} (parked past
        timeout_s — caller may re-request) | {infeasible: True}.

        ``token`` makes the grant idempotent: a retry carrying the same
        token (the owner's transport died after the grant but before the
        reply landed) gets the SAME grant back as long as that worker is
        still leased, instead of a second worker."""
        node = self._node
        if token is not None:
            cached = self._token_grant(token)
            if cached is not None:
                return cached
        if not _fits(demand, self.total_resources):
            with node._gcs_lock:
                target = node._gcs.call("pick_node", demand=demand,
                                        exclude=[node.node_id])
            addr = node._peer_address(target)
            if addr:
                return {"redirect": list(addr), "node_id": target}
            return {"infeasible": True}
        if spill_count < 1 and not _fits(demand, self.avail_snapshot()):
            # busy here: one spillback attempt through the GCS view
            # (mirror of rpc_submit_task's policy)
            with node._gcs_lock:
                target = node._gcs.call("pick_node", demand=demand,
                                        exclude=[node.node_id])
            addr = node._peer_address(target)
            if addr:
                return {"redirect": list(addr), "node_id": target}
        waiter = {"demand": demand, "runtime_env": runtime_env,
                  "event": threading.Event(), "result": None}
        with self.cv:
            self.lease_waiters.append(waiter)
            self.cv.notify()
        if not waiter["event"].wait(timeout=timeout_s):
            removed = True
            with self.cv:
                try:
                    self.lease_waiters.remove(waiter)
                except ValueError:
                    removed = False
            if not removed:
                # a granter claimed the waiter concurrently: it WILL set
                # the result (it already holds the worker + resources) —
                # block for it; dropping it would leak a leased worker
                # nobody ever dials
                waiter["event"].wait(timeout=5.0)
                if waiter["result"]:
                    self._cache_grant(token, waiter["result"])
                    return waiter["result"]
            return {"retry": True}
        self._cache_grant(token, waiter["result"])
        return waiter["result"]

    def _cache_grant(self, token: str | None, result: dict | None):
        if token is None or not (result and result.get("ok")):
            return
        with self._grant_lock:
            self._grant_tokens[token] = result
            while len(self._grant_tokens) > 1024:
                self._grant_tokens.popitem(last=False)

    def _token_grant(self, token: str) -> dict | None:
        """Replay a cached grant — but only while its worker is still in
        state ``leased`` (the owner may have dialed + finished + returned
        the lease between the retries; replaying then would hand out a
        stale address for a worker someone else now holds)."""
        with self._grant_lock:
            cached = self._grant_tokens.get(token)
        if cached is None:
            return None
        worker = self._node.workers.workers.get(cached.get("worker_id"))
        if worker is not None and worker.state == "leased":
            return cached
        with self._grant_lock:
            self._grant_tokens.pop(token, None)
        return None

    def _serve_lease_waiters(self):
        """Grant parked lease requests FIFO while workers + resources are
        available (runs on the dispatch thread)."""
        node = self._node
        pool = node.workers
        while True:
            with self.cv:
                if not self.lease_waiters:
                    return
                waiter = self.lease_waiters[0]
            env_err = pool.bad_env_error(waiter["runtime_env"])
            if env_err is not None:
                with self.cv:
                    try:
                        self.lease_waiters.remove(waiter)
                    except ValueError:
                        continue
                waiter["result"] = {"infeasible": True,
                                    "env_error": env_err}
                waiter["event"].set()
                continue
            worker = pool.idle_worker(waiter["runtime_env"])
            if worker is None:
                return  # spawn in progress / pool exhausted; kick revisits
            if worker.push_addr is None:
                # externally-registered worker with no push port (tests):
                # unusable for leases, put it back
                with pool.lock:
                    worker.state = "idle"
                return
            if not self.try_acquire(waiter["demand"]):
                with pool.lock:
                    worker.state = "idle"
                return  # resources busy; release kick revisits
            # the waiter may have timed out and removed itself while we
            # were acquiring — then the grant must be rolled back. The
            # rollback runs OUTSIDE the cv (lock order: never cv→locks).
            claimed = True
            with self.cv:
                try:
                    self.lease_waiters.remove(waiter)
                except ValueError:
                    claimed = False
            if not claimed:
                self.release(waiter["demand"])
                with pool.lock:
                    worker.state = "idle"
                continue
            # crash point: waiter claimed, resources acquired, grant not
            # yet sent — the owner's retry must land on a respawned node
            # or spill elsewhere (chaos soak raylet class)
            _fi.maybe_crash("raylet.before_lease_grant")
            with pool.lock:
                worker.state = "leased"
                worker.acquired = dict(waiter["demand"])
                worker.dispatched_at = time.monotonic()
            # arm the worker's never-dialed watchdog BEFORE the owner can
            # learn the address (guarantees msg-before-dial ordering)
            try:
                send_msg(worker.conn, {"type": "lease_granted"},
                         worker.send_lock)
            except OSError:
                pass
            waiter["result"] = {"ok": True,
                                "worker_addr": list(worker.push_addr),
                                "worker_id": worker.worker_id,
                                "node_id": node.node_id}
            waiter["event"].set()

    # ------------------------------------------------------------------
    # infeasible-task parking (reference: ClusterTaskManager infeasible
    # queue + GcsAutoscalerStateManager demand reporting)
    # ------------------------------------------------------------------

    def park_infeasible(self, task: dict, demand: dict):
        deadline = time.monotonic() + self.infeasible_timeout_s
        node = self._node
        with self._infeasible_lock:
            self._infeasible.append((task, demand, deadline))
            all_demands = [d for _, d, _ in self._infeasible]
        try:
            with node._gcs_lock:
                # full parked set: a per-task report would overwrite
                # siblings' demands in the GCS view
                node._gcs.call("report_demand", node_id=node.node_id,
                               demands=all_demands)
        except Exception:  # noqa: BLE001 - advertising only
            pass

    def take_infeasible_matching(self, matches) -> dict | None:
        """Pop (under the lock) the first parked infeasible task matching
        — the cancel path; error storing runs outside the lock."""
        with self._infeasible_lock:
            for i, (t, _, _) in enumerate(self._infeasible):
                if matches(t):
                    return self._infeasible.pop(i)[0]
        return None

    def infeasible_loop(self):
        """Retry parked tasks as capacity appears (a new node registers);
        error them when the grace window expires."""
        node = self._node
        while not node._stopping:
            time.sleep(0.25)
            with self._infeasible_lock:
                parked, self._infeasible = self._infeasible, []
            if not parked:
                continue
            still: list = []
            now = time.monotonic()
            demands_left = []
            for task, demand, deadline in parked:
                # this node's capacity is fixed; recovery means a NEW
                # node registered and the GCS can now place the task
                placed = False
                try:
                    with node._gcs_lock:
                        target = node._gcs.call(
                            "pick_node", demand=demand,
                            exclude=[node.node_id])
                    if target is not None and node._forward(
                            task, target, 0):
                        placed = True
                except Exception:  # noqa: BLE001
                    pass
                if placed:
                    continue
                if now > deadline:
                    node._store_task_error(task, ValueError(
                        f"task {task.get('name')} demands {demand}: "
                        f"infeasible (no node satisfied it within "
                        f"{self.infeasible_timeout_s}s)"))
                else:
                    still.append((task, demand, deadline))
                    demands_left.append(demand)
            with self._infeasible_lock:
                self._infeasible.extend(still)
            try:
                with node._gcs_lock:
                    node._gcs.call("report_demand", node_id=node.node_id,
                                   demands=demands_left)
            except Exception:  # noqa: BLE001
                pass
