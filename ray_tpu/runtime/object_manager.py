"""Per-node local object manager: primary-copy pins, the GCS location
directory view, spilling to disk, restore, and serving/pulling chunked
transfers.

Reference analog: ``src/ray/raylet/local_object_manager.cc`` (pin +
spill/restore of primaries), ``src/ray/object_manager/`` (chunked
transfer serving + PullManager), and the external-storage file backend
(``_private/external_storage.py``). A component OWNED by the raylet
(``runtime/raylet.py``): the raylet exposes thin ``rpc_*`` delegators
and passes itself in for GCS access and peer resolution.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from collections import deque

from ray_tpu._private.shm_store import ObjectNotFoundError
from ray_tpu.runtime import object_codec
from ray_tpu.util import metrics as _metrics
from ray_tpu.utils.ids import ObjectID

# memory-plane node occupancy series: updated from the 0.2s spill-loop
# tick (never from a put/spill hot path) and pushed by the raylet's
# MetricsPusher like every other plane
_g_mem_pinned = _metrics.gauge(
    "ray_tpu_mem_pinned_bytes",
    "primary-copy (raylet-pinned) bytes resident in the local store")
_g_mem_cached = _metrics.gauge(
    "ray_tpu_mem_cached_replica_bytes",
    "unpinned (pulled-secondary / releasable) bytes in the local store")
_g_mem_spilled = _metrics.gauge(
    "ray_tpu_mem_spilled_bytes", "bytes currently spilled to disk")
_g_mem_used = _metrics.gauge(
    "ray_tpu_mem_store_used_bytes", "shm store bytes allocated")
_c_make_room = _metrics.counter(
    "ray_tpu_mem_make_room_total",
    "make-room rounds triggered by writers hitting store-OOM")
_c_make_room_bytes = _metrics.counter(
    "ray_tpu_mem_make_room_spilled_bytes",
    "bytes spilled by writer-triggered make-room rounds")


class SpillStorage:
    """Spill target behind a tiny FS interface: a local directory (fast
    path: plain files, range reads by seek) or ANY pyarrow.fs URI —
    ``s3://bucket/prefix``, ``gs://...``, ``file:///...`` (reference:
    external_storage.py smart_open/S3 spilling). Cloud targets make
    spilled objects survive node loss and unbound by local disk."""

    def __init__(self, target: str):
        self._uri = "://" in target
        if self._uri:
            import pyarrow.fs as pafs

            self.fs, base = pafs.FileSystem.from_uri(target)
            self.base = base.rstrip("/")
        else:
            self.base = target

    def path(self, name: str) -> str:
        return f"{self.base}/{name}" if self._uri \
            else os.path.join(self.base, name)

    def write(self, path: str, payload: bytes):
        if not self._uri:
            os.makedirs(self.base, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
            return
        self.fs.create_dir(self.base, recursive=True)
        try:
            with self.fs.open_output_stream(path) as f:
                f.write(payload)
        except Exception:
            # URI writes go straight to the final name (cloud rename is
            # a copy): a failed stream must not leave a truncated object
            self.unlink(path)
            raise

    def read(self, path: str) -> bytes:
        if not self._uri:
            with open(path, "rb") as f:
                return f.read()
        with self.fs.open_input_stream(path) as f:
            return f.read()

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        if not self._uri:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        with self.fs.open_input_file(path) as f:
            f.seek(offset)
            return f.read(length)

    def exists(self, path: str) -> bool:
        try:
            if not self._uri:
                return os.path.exists(path)
            import pyarrow.fs as pafs

            info = self.fs.get_file_info(path)
            return info.type != pafs.FileType.NotFound
        except Exception:  # noqa: BLE001 - target unreachable: assume
            return True    # the file may still exist — never orphan it

    def unlink(self, path: str):
        try:
            if not self._uri:
                os.unlink(path)
            else:
                self.fs.delete_file(path)
        except Exception:  # noqa: BLE001 - already gone
            pass

    def cleanup(self):
        try:
            if not self._uri:
                shutil.rmtree(self.base, ignore_errors=True)
            else:
                self.fs.delete_dir_contents(self.base,
                                            missing_dir_ok=True)
        except Exception:  # noqa: BLE001 - best-effort
            pass


class LocalObjectManager:
    """Object lifecycle for one raylet node. ``node`` is the owning
    Raylet (identity, GCS client, peer table, stopping flag)."""

    def __init__(self, node, *, store, store_capacity: int, cfg):
        self._node = node
        self.store = store
        # --- object spilling (reference: LocalObjectManager::SpillObjects
        # local_object_manager.h:110 + external_storage.py
        # FileSystemStorage). Spilled objects leave shm for files in
        # _spill_dir; the GCS location entry stays (this node can still
        # serve them), and any local read restores them into shm first.
        self.spill_enabled = cfg.object_spilling_enabled
        self._spill_high = cfg.object_spilling_high_fraction
        self._spill_low = cfg.object_spilling_low_fraction
        # always a per-raylet SUBdirectory: stop() removes the whole dir,
        # and a shared configured path must not nuke other raylets' files.
        # The base may be a pyarrow.fs URI (s3:// gs:// file://) — cloud
        # spill targets (reference: external_storage.py).
        _spill_base = (cfg.object_spilling_directory
                       or tempfile.gettempdir())
        sub = f"raytpu_spill_{os.getpid()}_{node.node_id[:8]}"
        self.spill_is_local = "://" not in _spill_base
        self.spill_dir = (os.path.join(_spill_base, sub)
                          if self.spill_is_local
                          else f"{_spill_base.rstrip('/')}/{sub}")
        self._spill_fs = SpillStorage(self.spill_dir)
        # oid hex -> (file path, was_primary): primaries re-pin on
        # restore; spilled secondaries stay evictable after restore
        self._spilled: dict[str, tuple[str, bool]] = {}
        self._spill_lock = threading.Lock()
        # oids with a spill IN PROGRESS (guarded by _spill_lock): the
        # spill loop and request_space callers race otherwise — the
        # second spiller captures was_primary=False (the first already
        # unpinned) and OVERWRITES the entry, so the restore came back
        # unpinned and the object's only copy became LRU-evictable
        # (lost ~1 in 200k task returns under memory pressure)
        self._spilling: set[str] = set()
        self.spill_stats = {"num_spilled": 0, "bytes_spilled": 0,
                            "num_restored": 0, "bytes_restored": 0,
                            "spill_wall_s": 0.0, "restore_wall_s": 0.0}
        # memory plane: per-object size view (fed by location reports,
        # pulls, and spills) + current spilled-byte total — what the
        # occupancy decomposition prices the pinned/spilled sets with
        self._sizes: dict[str, int] = {}
        self._spilled_sizes: dict[str, int] = {}
        self._spilled_bytes = 0
        # recent writer-triggered make-room rounds, newest last: each is
        # {ts, requested, spilled: [oid,...], spilled_bytes} — the
        # cluster-level spill/OOM attribution joins these oids back to
        # their owners through the GCS ref table
        self._pressure_events: deque = deque(maxlen=64)
        # Primary-copy pins: every object CREATED on this node is pinned
        # (one raylet-held read ref) so the store's LRU eviction can never
        # destroy the sole copy — memory is reclaimed by SPILLING pinned
        # objects instead (reference: raylet PinObjectIDs + spill-only
        # reclamation of primaries; secondary/pulled copies stay
        # unpinned and evictable).
        self._pinned: set[str] = set()
        self._pin_lock = threading.Lock()
        # debug: trace pin/deregister history for scale-run loss hunts
        # (RAY_TPU_DEBUG_OBJECT_TRACE=/path enables; bounded cost)
        self._trace_path = os.environ.get("RAY_TPU_DEBUG_OBJECT_TRACE")
        self._ever_pinned: set[str] | None = (set() if self._trace_path
                                              else None)
        # every object registered with the GCS as located here (primary or
        # pulled secondary); reconciled against the store so LRU-evicted
        # secondaries don't leave stale locations in the directory forever
        # (reference: object-eviction pubsub updating the ObjectDirectory)
        self._local_objects: set[str] = set()
        self._local_objects_lock = threading.Lock()
        # oid -> (size, crc32): transfer-integrity probe memo (objects
        # are immutable; bounded FIFO)
        self._crc_cache: dict[str, tuple] = {}
        # buffered object-location registrations (batched to the GCS)
        self._loc_buf: list[tuple[str, int]] = []
        self._loc_cv = threading.Condition()
        # wakes ensure_local waiters when an object becomes local
        self._local_cv = threading.Condition()
        # chunked pull plane (reference: PullManager pull_manager.h:52)
        from ray_tpu.runtime.pull_manager import PullManager
        self.pulls = PullManager(
            fetch_local=self.restore_spilled,
            peer_addresses=self.peer_addresses_for,
            store=store,
            on_pulled=self._on_pulled,
            chunk_size=cfg.object_transfer_chunk_bytes,
            max_in_flight_bytes=max(
                int(store_capacity
                    * cfg.object_transfer_inflight_fraction),
                cfg.object_transfer_chunk_bytes),
            fault_label=getattr(node, "fault_label", None),
        )

    def stop(self):
        self.pulls.stop()

    def cleanup_disk(self):
        self._spill_fs.cleanup()

    def _trace(self, msg: str):
        if self._trace_path:
            try:
                with open(self._trace_path, "a") as f:
                    f.write(f"{self._node.node_id[:8]} {msg}\n")
            except OSError:
                pass

    # ------------------------------------------------------------------
    # local tracking + pins + the GCS directory view
    # ------------------------------------------------------------------

    def track_local(self, oid_hex: str):
        with self._local_objects_lock:
            self._local_objects.add(oid_hex)
        # wake ensure_local waiters (event-driven instead of polling for
        # the locally-produced-object case)
        with self._local_cv:
            self._local_cv.notify_all()

    def reconcile_locations(self):
        """Deregister objects that silently left the store (LRU-evicted
        secondaries): a stale directory entry would make owners pull from
        a node that cannot serve, and would mask true object loss from
        the lineage-reconstruction path."""
        node = self._node
        with self._local_objects_lock:
            snapshot = list(self._local_objects)
        gone = []
        for oid_hex in snapshot:
            # _spilled FIRST, store second: a concurrent restore pops
            # _spilled only AFTER the shm copy is secured+pinned, so this
            # order can never classify a mid-restore object as gone
            # (store-first could: miss the store, then miss _spilled
            # right after the restore completed)
            with self._spill_lock:
                if oid_hex in self._spilled:
                    continue   # spilled = still servable from disk
            if self.store.contains(bytes.fromhex(oid_hex)):
                continue
            gone.append(oid_hex)
        if not gone:
            return
        if self._ever_pinned is not None:
            for oid_hex in gone:
                if oid_hex in self._ever_pinned:
                    self._trace(f"RECONCILE-DROP-PINNED {oid_hex} "
                                f"pinned_now={self.is_pinned(oid_hex)}")
        with self._local_objects_lock:
            self._local_objects.difference_update(gone)
        with self._pin_lock:
            self._pinned.difference_update(gone)
        for oid_hex in gone:
            self._sizes.pop(oid_hex, None)
        for oid_hex in gone:
            try:
                with node._gcs_lock:
                    node._gcs.call("remove_object_location", oid=oid_hex,
                                   node_id=node.node_id)
            except Exception:  # noqa: BLE001 - gcs down; retried next tick
                with self._local_objects_lock:
                    self._local_objects.add(oid_hex)

    def pin_object(self, oid_hex: str):
        """Pin a newly created primary copy (idempotent)."""
        with self._pin_lock:
            if oid_hex in self._pinned:
                return
            if self.store.pin(bytes.fromhex(oid_hex)):
                self._pinned.add(oid_hex)
                if self._ever_pinned is not None:
                    self._ever_pinned.add(oid_hex)
            elif self._ever_pinned is not None:
                self._trace(f"PIN-FAILED {oid_hex}")

    def unpin_object(self, oid_hex: str):
        with self._pin_lock:
            if oid_hex in self._pinned:
                self._pinned.discard(oid_hex)
                self.store.unpin(bytes.fromhex(oid_hex))

    def _capture_and_unpin(self, oid_hex: str) -> bool:
        """Atomically read-and-clear the pin (spill_one's primary-copy
        capture). One locked section: a pin landing between a separate
        capture and unpin would be silently erased — the spilled entry
        would record was_primary=False, its restore would come back
        UNPINNED, and LRU eviction could then destroy the object's only
        copy (seen once per ~200k task returns under spill pressure)."""
        with self._pin_lock:
            was = oid_hex in self._pinned
            if was:
                self._pinned.discard(oid_hex)
                self.store.unpin(bytes.fromhex(oid_hex))
            return was

    def is_pinned(self, oid_hex: str) -> bool:
        with self._pin_lock:
            return oid_hex in self._pinned

    def report_object(self, oid: str, size: int = 0) -> bool:
        """A local process created an object: pin the primary copy and
        register the location with the GCS (reference: the Put path's
        PinObjectIDs + object directory update). Callers seal with a held
        ref (``seal(hold=True)``) so the object cannot vanish before the
        pin lands here.

        The PIN is synchronous (it is what makes the object durable); the
        GCS directory registration is BUFFERED and flushed in batches —
        one directory RPC per flush, not per task return, keeping the
        head-node round trip off the task hot path (reference: the
        ownership-based object directory is similarly not on the task
        completion critical path)."""
        self.pin_object(oid)
        if not self.is_pinned(oid):
            # the object may have been spilled BEFORE this pin landed
            # (memory pressure racing the batched report): the spill
            # entry then says was_primary=False — promote it, or its
            # restore would come back unpinned and evictable as the
            # object's only copy
            with self._spill_lock:
                entry = self._spilled.get(oid)
                if entry is not None and not entry[1]:
                    self._spilled[oid] = (entry[0], True)
            if entry is None and not self.store.contains(
                    bytes.fromhex(oid)):
                # should be unreachable under the hold protocol; never
                # advertise a location that cannot serve the object
                return False
        self.track_local(oid)
        self.queue_location(oid, size)
        return True

    def queue_location(self, oid: str, size: int):
        if size:
            self._sizes[oid] = size   # GIL-atomic; occupancy pricing
        with self._loc_cv:
            self._loc_buf.append((oid, size))
            self._loc_cv.notify()

    def location_flush_loop(self):
        """Drain the location buffer into batched GCS registrations. A
        short linger coalesces bursts; an empty buffer blocks on the cv
        (no polling)."""
        node = self._node
        while not node._stopping:
            with self._loc_cv:
                if not self._loc_buf:
                    self._loc_cv.wait(timeout=0.2)
                if not self._loc_buf:
                    continue
                time_to_linger = 0.002
            time.sleep(time_to_linger)  # let the burst accumulate
            with self._loc_cv:
                batch, self._loc_buf = self._loc_buf, []
            if not batch:
                continue
            try:
                with node._gcs_lock:
                    node._gcs.call("add_object_locations",
                                   node_id=node.node_id, entries=batch)
            except Exception:  # noqa: BLE001 - GCS down; heartbeat
                pass           # reconciliation re-registers local objects

    # ------------------------------------------------------------------
    # explicit free (reference: ray.internal.free)
    # ------------------------------------------------------------------

    def free_objects(self, oids: list, deregister: bool = True) -> int:
        """Release local copies: unpin, drop from shm and the spill dir,
        deregister locations. Returns the number of copies freed.

        ``deregister=False``: the free was INITIATED by the GCS
        (refcount hit zero — the directory entry is already gone), so
        skip the remove_object_location round trips and the lost-object
        tombstoning they would cause."""
        from ray_tpu._private.shm_store import TS_ERR, TS_OK

        node = self._node
        freed = 0
        pending: list[tuple[str, bool, bool]] = []  # (oid, pinned, spilled)
        for oid_hex in oids:
            if node._stopping:
                return freed   # store is about to unmap: never touch it
            was_pinned = self.is_pinned(oid_hex)
            self.unpin_object(oid_hex)
            with self._spill_lock:
                entry = self._spilled.pop(oid_hex, None)
                self._spilled_bytes -= self._spilled_sizes.pop(oid_hex, 0)
            if entry is not None:
                self._spill_fs.unlink(entry[0])
                freed += 1
            pending.append((oid_hex, was_pinned, entry is not None))
        # drain in-flight refs (a writer's seal-hold released right after
        # its report RPC, or a reader mid-get) with ONE shared ~200ms
        # budget across all oids, not per object
        done: list[tuple[str, bool, int]] = []
        deadline = time.monotonic() + 0.2
        while pending:
            still = []
            for oid_hex, was_pinned, had_spill in pending:
                if node._stopping:
                    return freed   # mid-batch shutdown: bail before the
                    # munmap (a large refcount release riding a heartbeat
                    # was segfaulting here at teardown)
                rc = self.store.try_delete(bytes.fromhex(oid_hex))
                if rc == TS_ERR and time.monotonic() < deadline:
                    still.append((oid_hex, was_pinned, had_spill))
                else:
                    done.append((oid_hex, had_spill, rc))
                    if rc == TS_ERR and was_pinned:
                        # a reader outlived the drain: the surviving
                        # primary stays authoritative — re-pin it so LRU
                        # eviction cannot silently orphan the stale GCS
                        # location (same rule as spill_one)
                        self.pin_object(oid_hex)
            pending = still
            if pending:
                time.sleep(0.01)
        for oid_hex, had_spill, rc in done:
            if rc == TS_OK and not had_spill:
                freed += 1
            if rc == TS_ERR:
                continue   # copy stays: tracked, registered, re-pinned
            with self._local_objects_lock:
                was_local = oid_hex in self._local_objects
                self._local_objects.discard(oid_hex)
            self._sizes.pop(oid_hex, None)
            if deregister and (was_local or had_spill):
                try:
                    with node._gcs_lock:
                        node._gcs.call("remove_object_location",
                                       oid=oid_hex, node_id=node.node_id)
                except Exception:  # noqa: BLE001 - best-effort
                    pass
        return freed

    # ------------------------------------------------------------------
    # spilling (reference: LocalObjectManager + ExternalStorage — spill
    # LRU-cold objects to files under memory pressure, restore on read)
    # ------------------------------------------------------------------

    def request_space(self, nbytes: int = 0) -> int:
        """A writer hit store-OOM: synchronously spill pinned-idle objects
        to make room (reference: CreateRequestQueue retry + triggered
        spill). Returns the number of objects spilled."""
        if not self.spill_enabled:
            return 0  # honor the no-disk-writes contract
        # floor scaled to the allocation (2x for headroom) and the store
        # (1/8 capacity) — a fixed large floor would thrash small stores
        cap = self.store.capacity
        target = min(max(2 * int(nbytes), cap // 8), cap)
        spilled: list[str] = []
        n = self.spill_bytes(target, collect=spilled)
        if n == 0:
            # nothing pinned-idle; last resort, spill unpinned cold
            # entries too (they are evictable anyway — spilling keeps
            # them readable instead of destroying them)
            for oid in self.store.spill_candidates(target, pin_pid=0):
                oid_hex = oid[:ObjectID.SIZE].hex()
                if self.spill_one(oid[:ObjectID.SIZE]):
                    n += 1
                    spilled.append(oid_hex)
        # make-room attribution: record WHICH objects a pressured writer
        # forced out; memory_summary joins these oids to their owners
        spilled_bytes = sum(self._sizes.get(o, 0)
                            or self._spilled_sizes.get(o, 0)
                            for o in spilled)
        self._pressure_events.append({
            "ts": time.time(), "requested": int(nbytes),
            "spilled": spilled, "spilled_bytes": spilled_bytes})
        if _metrics.enabled():
            _c_make_room.inc()
            if spilled_bytes:
                _c_make_room_bytes.inc(spilled_bytes)
        return n

    def spill_bytes(self, target: int, collect: list | None = None) -> int:
        n = 0
        for oid in self.store.spill_candidates(target,
                                               pin_pid=os.getpid()):
            oid_hex = oid[:ObjectID.SIZE].hex()
            if self.spill_one(oid[:ObjectID.SIZE]):
                n += 1
                if collect is not None:
                    collect.append(oid_hex)
        return n

    def spill_loop(self):
        node = self._node
        tick = 0
        while not node._stopping:
            time.sleep(0.2)
            tick += 1
            if tick % 10 == 0:
                # occupancy gauges on a ~2s cadence (the metrics push
                # period): pricing the pinned set is O(pinned objects),
                # too heavy for every 0.2s spill tick at scale
                try:
                    self.publish_occupancy_metrics()
                except Exception:  # noqa: BLE001 - best-effort plane
                    pass
            try:
                st = self.store.stats()
            except Exception:  # noqa: BLE001 - store closing
                return
            cap = st["capacity"] or 1
            if st["bytes_allocated"] <= self._spill_high * cap:
                continue
            self.spill_bytes(
                st["bytes_allocated"] - int(self._spill_low * cap))

    def spill_one(self, oid: bytes) -> bool:
        """Copy one sealed object out to a file, then drop it from shm.
        Exclusive per oid: concurrent spillers (the spill loop racing a
        request_space caller) corrupt the was_primary flag."""
        oid_hex = oid.hex()
        with self._spill_lock:
            if oid_hex in self._spilling or oid_hex in self._spilled:
                return False
            self._spilling.add(oid_hex)
        try:
            return self._spill_one_locked(oid, oid_hex)
        finally:
            with self._spill_lock:
                self._spilling.discard(oid_hex)

    def _spill_one_locked(self, oid: bytes, oid_hex: str) -> bool:
        t0 = time.perf_counter()
        try:
            payload = object_codec.raw_bytes(self.store, oid, timeout_ms=0)
        except Exception:  # noqa: BLE001 - vanished (freed/evicted) — fine
            return False
        path = self._spill_fs.path(oid_hex)
        try:
            self._spill_fs.write(path, payload)
        except Exception:  # noqa: BLE001 - target full/unreachable
            self._spill_fs.unlink(path + ".tmp")
            return False
        from ray_tpu._private.shm_store import TS_ERR, TS_OK

        was_primary = self._capture_and_unpin(oid_hex)
        with self._spill_lock:
            self._spilled[oid_hex] = (path, was_primary)
            self._spilled_sizes[oid_hex] = len(payload)
            self._spilled_bytes += len(payload)
        rc = self.store.try_delete(oid)
        if rc == TS_ERR:
            # a reader still holds a ref: keep the shm copy authoritative —
            # re-pin, discard the file
            self.pin_object(oid_hex)
            with self._spill_lock:
                self._spilled.pop(oid_hex, None)
                self._spilled_bytes -= self._spilled_sizes.pop(oid_hex, 0)
            self._spill_fs.unlink(path)
            return False
        # TS_OK: we removed it. TS_NOT_FOUND: a concurrent evict/spill beat
        # us to it — the file we just wrote may now be the ONLY copy, so it
        # must stay registered either way.
        self._sizes.setdefault(oid_hex, len(payload))
        self.spill_stats["num_spilled"] += 1
        self.spill_stats["bytes_spilled"] += len(payload)
        self.spill_stats["spill_wall_s"] += time.perf_counter() - t0
        return rc == TS_OK

    def restore_spilled(self, oid_hex: str) -> bool:
        """Load a locally-spilled object back into shm (for readers)."""
        t0 = time.perf_counter()
        with self._spill_lock:
            entry = self._spilled.get(oid_hex)
        if entry is None:
            return False
        path, was_primary = entry
        try:
            payload = self._spill_fs.read(path)
        except Exception:  # noqa: BLE001 - file gone OR target down
            # drop the entry only when the file is CONFIRMED absent — a
            # transient cloud-backend error (throttle, reset) must not
            # orphan the sole copy of a spilled primary
            if not self._spill_fs.exists(path):
                with self._spill_lock:
                    self._spilled.pop(oid_hex, None)
                    self._spilled_bytes -= self._spilled_sizes.pop(
                        oid_hex, 0)
            return False
        from ray_tpu._private.shm_store import (ObjectExistsError,
                                                StoreFullError)

        oid = bytes.fromhex(oid_hex)
        held = False
        for _ in range(8):
            try:
                # hold through the seal: the restored entry must never sit
                # at refcount 0 where eviction/spill could destroy it
                # before we pin + unlink the file
                object_codec.put_raw(self.store, oid, payload, hold=True)
                held = True
                break
            except ObjectExistsError:
                break  # racing restore won; theirs is pinned
            except StoreFullError:
                # make room by spilling OTHER pinned-idle objects
                if self.spill_bytes(len(payload)) == 0:
                    time.sleep(0.05)  # wait for readers to release
            except Exception:  # noqa: BLE001 - racing restore
                break
        if was_primary:
            self.pin_object(oid_hex)   # restored primary: pin again
        if held:
            self.store.release(oid)
        if was_primary:
            ok = self.is_pinned(oid_hex)
        else:
            # secondary: stays unpinned/evictable; success = it is present
            ok = held or self.store.contains(oid)
        if not ok:
            # could not secure the shm copy — the file stays the
            # authoritative copy; do NOT unlink
            return self.store.contains(oid)
        with self._spill_lock:
            self._spilled.pop(oid_hex, None)
            self._spilled_bytes -= self._spilled_sizes.pop(oid_hex, 0)
        self._spill_fs.unlink(path)
        self.spill_stats["num_restored"] += 1
        self.spill_stats["bytes_restored"] += len(payload)
        self.spill_stats["restore_wall_s"] += time.perf_counter() - t0
        return True

    def read_spilled(self, oid_hex: str) -> bytes | None:
        """Read a spilled object's bytes without restoring it to shm
        (serving a remote fetch should not churn local memory)."""
        with self._spill_lock:
            entry = self._spilled.get(oid_hex)
        if entry is None:
            return None
        try:
            return self._spill_fs.read(entry[0])
        except Exception:  # noqa: BLE001
            return None

    # ------------------------------------------------------------------
    # transfer serving (reference: object_manager.cc chunked transfer)
    # ------------------------------------------------------------------

    def fetch_object(self, oid: str) -> bytes:
        """The encoded object bytes from the local store (or spill file)."""
        try:
            return object_codec.raw_bytes(self.store, bytes.fromhex(oid),
                                          timeout_ms=0)
        except ObjectNotFoundError:
            payload = self.read_spilled(oid)
            if payload is None:
                raise
            return payload

    def fetch_object_meta(self, oid: str) -> dict:
        """Size + CRC probe for the pull path (reference: the object
        directory carries sizes for PullManager admission; the checksum
        is transfer integrity — the destination verifies the assembled
        bytes before SEALING, so a torn read can never become a readable
        object). Objects are immutable, so size+CRC memoize per oid —
        repeat probes (N pullers, retries) cost a dict hit, not an
        O(size) pass on the handler thread."""
        import zlib

        cached = self._crc_cache.get(oid)
        if cached is not None:
            return {"found": True, "size": cached[0], "crc32": cached[1]}
        oid_b = bytes.fromhex(oid)
        try:
            view = self.store.get(oid_b, timeout_ms=0)
            try:
                size, crc = view.nbytes, zlib.crc32(view)
            finally:
                view.release()
                self.store.release(oid_b)
        except ObjectNotFoundError:
            data = self.read_spilled(oid)
            if data is None:
                return {"found": False}
            size, crc = len(data), zlib.crc32(data)
        self._crc_cache[oid] = (size, crc)
        while len(self._crc_cache) > 4096:
            self._crc_cache.pop(next(iter(self._crc_cache)))
        return {"found": True, "size": size, "crc32": crc}

    def fetch_object_chunk(self, oid: str, offset: int, length: int) -> bytes:
        """One chunk of an object's raw encoding (reference:
        ObjectManager chunked transfer, 5 MiB default chunks —
        object_manager.cc:339). Spilled objects are served by file seek —
        no whole-object restore to answer a remote read."""
        oid_b = bytes.fromhex(oid)
        try:
            view = self.store.get(oid_b, timeout_ms=0)
            try:
                return bytes(view[offset:offset + length])
            finally:
                view.release()
                self.store.release(oid_b)
        except ObjectNotFoundError:
            with self._spill_lock:
                entry = self._spilled.get(oid)
            if entry is None:
                raise
            return self._spill_fs.read_range(entry[0], offset, length)

    # ------------------------------------------------------------------
    # pulls (reference: PullManager)
    # ------------------------------------------------------------------

    def ensure_local(self, oids: list, timeout_s: float = 30.0) -> list:
        """Make objects locally readable, pulling from peers as needed.
        Returns the list of oids that could NOT be made local in time.
        Waits are event-driven for locally-produced objects (the common
        case): report_object notifies ``_local_cv``.

        Locations are resolved in BATCHED directory queries per wave:
        per-oid GCS lookups inside the pull path cost one RPC per
        not-yet-produced object per poll — at a 200k-object get that
        melted the control plane."""
        node = self._node
        deadline = time.monotonic() + timeout_s
        missing = [o for o in oids
                   if not self.store.contains(bytes.fromhex(o))]
        while missing and time.monotonic() < deadline:
            locs: dict = {}
            for i in range(0, len(missing), 5000):
                part = missing[i:i + 5000]
                try:
                    with node._gcs_lock:
                        locs.update(node._gcs.call(
                            "get_object_locations", oids=part))
                except Exception:  # noqa: BLE001 - GCS busy: retry wave
                    break
            still = []
            for oid_hex in missing:
                oid = bytes.fromhex(oid_hex)
                if self.store.contains(oid):
                    continue
                holders = locs.get(oid_hex) or []
                sources = []
                local_hint = False
                for nid in holders:
                    if nid == node.node_id:
                        local_hint = True   # spilled here: restore path
                        continue
                    addr = node._peer_address(nid)
                    if addr is not None:
                        sources.append((nid, addr))
                if not sources and not local_hint:
                    still.append(oid_hex)   # not produced anywhere yet
                    continue
                if not self.pulls.pull(oid_hex, known_sources=sources):
                    still.append(oid_hex)
            missing = still
            if missing:
                # wake instantly when a local task seals one of ours;
                # re-check remote locations on a coarser cadence
                with self._local_cv:
                    self._local_cv.wait(
                        timeout=min(0.2, max(deadline - time.monotonic(),
                                             0.0)))
        return missing

    def peer_addresses_for(self, oid_hex: str) -> list:
        node = self._node
        with node._gcs_lock:
            locs = node._gcs.call("get_object_locations",
                                  oids=[oid_hex])[oid_hex]
        out = []
        for node_id in locs:
            if node_id == node.node_id:
                continue
            addr = node._peer_address(node_id)
            if addr is not None:
                out.append((node_id, addr))
        return out

    def _on_pulled(self, oid_hex: str, size: int):
        self.track_local(oid_hex)
        self.queue_location(oid_hex, size)

    # ------------------------------------------------------------------
    # memory plane: node occupancy decomposition
    # ------------------------------------------------------------------

    def occupancy(self) -> dict:
        """Where this node's object memory is: pinned primaries vs
        unpinned cached replicas vs spilled files, plus cumulative
        spill/restore/eviction accounting and the in-flight pull load
        (reference analog: the per-node breakdown in `ray memory`'s
        store stats footer)."""
        try:
            st = self.store.stats()
        except Exception:  # noqa: BLE001 - store closing
            st = {"capacity": 0, "bytes_allocated": 0, "num_objects": 0,
                  "num_evictions": 0, "bytes_evicted": 0}
        with self._pin_lock:
            pinned = list(self._pinned)
        sizes = self._sizes
        pinned_bytes = 0
        for o in pinned:
            pinned_bytes += sizes.get(o, 0)
        with self._spill_lock:
            num_spilled_now = len(self._spilled)
            spilled_bytes = self._spilled_bytes
        pull = self.pulls.stats()
        return {
            "capacity_bytes": st.get("capacity", 0),
            "allocated_bytes": st.get("bytes_allocated", 0),
            "num_objects": st.get("num_objects", 0),
            "num_pinned": len(pinned),
            # primaries ARE the pinned set in this runtime: every object
            # created on the node is pinned by its raylet until spill
            "pinned_bytes": pinned_bytes,
            "primary_bytes": pinned_bytes,
            "cached_replica_bytes": max(
                0, st.get("bytes_allocated", 0) - pinned_bytes),
            "spilled_bytes": spilled_bytes,
            "num_spilled_now": num_spilled_now,
            "num_evictions": st.get("num_evictions", 0),
            "bytes_evicted": st.get("bytes_evicted", 0),
            "being_pulled": pull.get("num_active", 0),
            "being_pulled_bytes": pull.get("in_flight_bytes", 0),
            "spill_stats": dict(self.spill_stats),
            "pressure_events": list(self._pressure_events)[-16:],
            "ts": time.time(),
        }

    def spilled_oids(self, limit: int = 512) -> list[str]:
        """Currently-spilled oids (capped, largest first) for per-object
        state classification in list_objects / memory_summary."""
        with self._spill_lock:
            rows = sorted(self._spilled_sizes.items(),
                          key=lambda kv: -kv[1])
        return [oid for oid, _ in rows[:limit]]

    def being_pulled(self) -> set:
        """oids with a pull in flight right now (annotates list_objects
        / ownership state with 'being-pulled')."""
        return self.pulls.active_oids()

    def spilled_state(self, oid_hex: str) -> bool:
        with self._spill_lock:
            return oid_hex in self._spilled

    def publish_occupancy_metrics(self):
        """Refresh the ray_tpu_mem_* gauges (spill-loop tick cadence)."""
        if not _metrics.enabled():
            return
        occ = self.occupancy()
        _g_mem_pinned.set(occ["pinned_bytes"])
        _g_mem_cached.set(occ["cached_replica_bytes"])
        _g_mem_spilled.set(occ["spilled_bytes"])
        _g_mem_used.set(occ["allocated_bytes"])
