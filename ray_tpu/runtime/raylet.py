"""Raylet: per-node manager — scheduler, worker pool, object manager.

Reference analog: ``src/ray/raylet/`` — ``NodeManager`` (node_manager.h:125)
on one event loop hosting the local scheduler (``ClusterTaskManager`` /
``LocalTaskManager``), the worker pool (``worker_pool.cc``), and the object
manager (``src/ray/object_manager/`` — pull/push of objects between nodes).

Differences by design (TPU-host build, single-controller Python services):
- workers attach the node's C++ shm store directly (no UDS protocol hop);
- spillback consults the GCS resource view instead of gossiped snapshots
  (the ray_syncer analog is the heartbeat's available-resources report);
- node-to-node object transfer is a pull-only fetch RPC (the reference's
  PushManager handles proactive pushes; pull covers get()/dependency flow).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from ray_tpu._private.shm_store import ObjectNotFoundError, ShmObjectStore
from ray_tpu.runtime import object_codec
from ray_tpu.runtime.gcs import _fits
from ray_tpu.runtime.rpc import (
    ReconnectingRpcClient,
    RpcClient,
    RpcServer,
    recv_msg,
    send_msg,
)
from ray_tpu.utils.ids import ObjectID, WorkerID


@dataclass
class WorkerHandle:
    worker_id: str
    proc: subprocess.Popen | None = None
    conn: Any = None            # held task-channel socket
    send_lock: Any = None
    state: str = "starting"     # starting | idle | busy | leased | actor | dead
    # owner-facing task port (worker-lease protocol); leases hand this
    # address to the owner, which pushes tasks to it directly
    push_addr: tuple | None = None
    actor_id: str | None = None
    incarnation: int = 0
    current_task: dict | None = None
    acquired: dict = field(default_factory=dict)
    # set by the memory monitor right before a pressure kill so the death
    # handler stores OutOfMemoryError instead of WorkerCrashedError
    oom_killed: bool = False
    dispatched_at: float = 0.0   # monotonic time the current task started
    # runtime-env identity this worker booted with; tasks only run on a
    # worker with a matching key (reference: (language, runtime_env)-
    # keyed worker caching in worker_pool.cc)
    env_key: str = ""


class Raylet(RpcServer):
    def __init__(self, *, node_id: str, gcs_address, resources: dict,
                 store_capacity: int = 1 << 30, host: str = "127.0.0.1",
                 labels: dict | None = None, heartbeat_interval_s: float = 0.5,
                 infeasible_timeout_s: float = 10.0):
        super().__init__(host, 0)
        self.node_id = node_id
        self.gcs_address = tuple(gcs_address)
        self.store_name = f"/raytpu_{os.getpid()}_{node_id[:8]}"
        self.store = ShmObjectStore(self.store_name, capacity=store_capacity,
                                    create=True)
        self.total_resources = dict(resources)
        self.available = dict(resources)
        self.labels = labels or {}
        self._res_lock = threading.Lock()

        # reconnecting: survives a GCS restart (file-backed recovery)
        self._gcs = ReconnectingRpcClient(self.gcs_address)
        self._gcs_lock = threading.Lock()   # RpcClient is thread-safe; lock
                                            # keeps call+interpret atomic
        self._peers: dict[str, RpcClient] = {}
        self._peer_addrs: dict[str, tuple] = {}
        self._peers_lock = threading.Lock()

        self._workers: dict[str, WorkerHandle] = {}
        self._workers_lock = threading.Lock()
        self._max_workers = max(1, int(resources.get("CPU", 1)))
        self._ready: deque[dict] = deque()
        self._ready_cv = threading.Condition()
        # bumped on every completion/registration: the dispatch loop
        # re-checks it under the cv so a kick racing the wait is never lost
        self._dispatch_gen = 0
        self._hb_interval = heartbeat_interval_s
        self._threads: list[threading.Thread] = []
        # --- object spilling (reference: LocalObjectManager::SpillObjects
        # local_object_manager.h:110 + external_storage.py FileSystemStorage).
        # Spilled objects leave shm for files in _spill_dir; the GCS
        # location entry stays (this node can still serve them), and any
        # local read restores them into shm first.
        from ray_tpu.utils.config import get_config
        _cfg = get_config()
        self._spill_enabled = _cfg.object_spilling_enabled
        self._mem_threshold = _cfg.memory_usage_threshold
        self._mem_refresh_s = max(_cfg.memory_monitor_refresh_ms, 50) / 1e3
        self._spill_high = _cfg.object_spilling_high_fraction
        self._spill_low = _cfg.object_spilling_low_fraction
        # always a per-raylet SUBdirectory: stop() removes the whole dir,
        # and a shared configured path must not nuke other raylets' files
        _spill_base = (_cfg.object_spilling_directory
                       or tempfile.gettempdir())
        self._spill_dir = os.path.join(
            _spill_base, f"raytpu_spill_{os.getpid()}_{node_id[:8]}")
        # oid hex -> (file path, was_primary): primaries re-pin on
        # restore; spilled secondaries stay evictable after restore
        self._spilled: dict[str, tuple[str, bool]] = {}
        self._spill_lock = threading.Lock()
        self.spill_stats = {"num_spilled": 0, "bytes_spilled": 0,
                            "num_restored": 0, "bytes_restored": 0}
        # Primary-copy pins: every object CREATED on this node is pinned
        # (one raylet-held read ref) so the store's LRU eviction can never
        # destroy the sole copy — memory is reclaimed by SPILLING pinned
        # objects instead (reference: raylet PinObjectIDs + spill-only
        # reclamation of primaries; secondary/pulled copies stay
        # unpinned and evictable).
        self._pinned: set[str] = set()
        self._pin_lock = threading.Lock()
        # every object registered with the GCS as located here (primary or
        # pulled secondary); reconciled against the store so LRU-evicted
        # secondaries don't leave stale locations in the directory forever
        # (reference: object-eviction pubsub updating the ObjectDirectory)
        self._local_objects: set[str] = set()
        self._local_objects_lock = threading.Lock()
        # cluster-wide infeasible tasks awaiting capacity (autoscaler)
        self.infeasible_timeout_s = infeasible_timeout_s
        self._infeasible: list = []
        self._infeasible_lock = threading.Lock()
        # OOM-backoff timers (cancelled by stop())
        self._deferred_timers: set[threading.Timer] = set()
        self._timers_lock = threading.Lock()
        # why recent workers died, queried by lease owners on break
        # (bounded FIFO; reference: worker exit detail in death reports)
        self._death_info: dict[str, dict] = {}
        # env_key -> (error, when): envs whose setup failed — tasks fail
        # fast instead of driving a spawn/install/crash loop
        self._bad_envs: dict[str, tuple] = {}
        # oid -> (size, crc32): transfer-integrity probe memo (objects
        # are immutable; bounded FIFO)
        self._crc_cache: dict[str, tuple] = {}
        # buffered object-location registrations (batched to the GCS)
        self._loc_buf: list[tuple[str, int]] = []
        self._loc_cv = threading.Condition()
        # wakes ensure_local waiters when an object becomes local
        self._local_cv = threading.Condition()
        # chunked pull plane (reference: PullManager pull_manager.h:52)
        from ray_tpu.runtime.pull_manager import PullManager
        self._pulls = PullManager(
            fetch_local=self._restore_spilled,
            peer_addresses=self._peer_addresses_for,
            store=self.store,
            on_pulled=self._on_pulled,
            chunk_size=_cfg.object_transfer_chunk_bytes,
            max_in_flight_bytes=max(
                int(store_capacity
                    * _cfg.object_transfer_inflight_fraction),
                _cfg.object_transfer_chunk_bytes),
        )
        # parked worker-lease requests (owner-side lease protocol;
        # reference: the lease queue behind HandleRequestWorkerLease,
        # node_manager.cc:1778). Guarded by _ready_cv.
        self._lease_waiters: deque[dict] = deque()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self):
        super().start()
        with self._gcs_lock:
            self._gcs.call(
                "register_node", node_id=self.node_id, address=self.address,
                store_name=self.store_name, resources=self.total_resources,
                labels=self.labels)
        loops = [self._dispatch_loop, self._heartbeat_loop,
                 self._monitor_loop, self._infeasible_loop,
                 self._location_flush_loop]
        if self._spill_enabled:
            loops.append(self._spill_loop)
        if self._mem_threshold > 0:
            loops.append(self._memory_monitor_loop)
        for target in loops:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    # ------------------------------------------------------------------
    # infeasible-task parking (reference: ClusterTaskManager infeasible
    # queue + GcsAutoscalerStateManager demand reporting)
    # ------------------------------------------------------------------

    def _park_infeasible(self, task: dict, demand: dict):
        deadline = time.monotonic() + self.infeasible_timeout_s
        with self._infeasible_lock:
            self._infeasible.append((task, demand, deadline))
            all_demands = [d for _, d, _ in self._infeasible]
        try:
            with self._gcs_lock:
                # full parked set: a per-task report would overwrite
                # siblings' demands in the GCS view
                self._gcs.call("report_demand", node_id=self.node_id,
                               demands=all_demands)
        except Exception:  # noqa: BLE001 - advertising only
            pass

    def _infeasible_loop(self):
        """Retry parked tasks as capacity appears (a new node registers);
        error them when the grace window expires."""
        while not self._stopping:
            time.sleep(0.25)
            with self._infeasible_lock:
                parked, self._infeasible = self._infeasible, []
            if not parked:
                continue
            still: list = []
            now = time.monotonic()
            demands_left = []
            for task, demand, deadline in parked:
                # this node's capacity is fixed; recovery means a NEW
                # node registered and the GCS can now place the task
                placed = False
                try:
                    with self._gcs_lock:
                        target = self._gcs.call(
                            "pick_node", demand=demand,
                            exclude=[self.node_id])
                    if target is not None and self._forward(
                            task, target, 0):
                        placed = True
                except Exception:  # noqa: BLE001
                    pass
                if placed:
                    continue
                if now > deadline:
                    self._store_task_error(task, ValueError(
                        f"task {task.get('name')} demands {demand}: "
                        f"infeasible (no node satisfied it within "
                        f"{self.infeasible_timeout_s}s)"))
                else:
                    still.append((task, demand, deadline))
                    demands_left.append(demand)
            with self._infeasible_lock:
                self._infeasible.extend(still)
            try:
                with self._gcs_lock:
                    self._gcs.call("report_demand", node_id=self.node_id,
                                   demands=demands_left)
            except Exception:  # noqa: BLE001
                pass

    def stop(self):
        super().stop()
        self._pulls.stop()
        with self._timers_lock:
            timers = list(self._deferred_timers)
            self._deferred_timers.clear()
        for timer in timers:
            timer.cancel()
        # wake parked lease requests so owners fall back instead of
        # blocking out their full timeout on a dying node
        with self._ready_cv:
            waiters = list(self._lease_waiters)
            self._lease_waiters.clear()
        for waiter in waiters:
            waiter["result"] = {"retry": True}
            waiter["event"].set()
        # join background loops BEFORE closing the store: a mid-tick spill
        # loop dereferencing the munmapped segment is a segfault, not an
        # exception
        for t in self._threads:
            t.join(timeout=2.0)
        with self._workers_lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
        self.store.close()
        shutil.rmtree(self._spill_dir, ignore_errors=True)

    # ------------------------------------------------------------------
    # worker pool (reference: worker_pool.cc — spawn, registration
    # handshake, idle caching)
    # ------------------------------------------------------------------

    def _spawn_worker(self, runtime_env: dict | None = None) -> WorkerHandle:
        from ray_tpu.runtime_env import env_key as _env_key

        worker_id = WorkerID.from_random().hex()
        env = dict(os.environ)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        if runtime_env:
            env["RAY_TPU_RUNTIME_ENV"] = json.dumps(runtime_env)
        env.update({
            "RAY_TPU_RAYLET_HOST": self.address[0],
            "RAY_TPU_RAYLET_PORT": str(self.address[1]),
            "RAY_TPU_GCS_HOST": self.gcs_address[0],
            "RAY_TPU_GCS_PORT": str(self.gcs_address[1]),
            "RAY_TPU_STORE_NAME": self.store_name,
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_NODE_ID": self.node_id,
            # workers never touch the TPU tunnel unless told to
            "JAX_PLATFORMS": env_get_default("JAX_PLATFORMS", "cpu"),
        })
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.worker_main"],
            env=env, cwd=os.getcwd(),
        )
        handle = WorkerHandle(worker_id=worker_id, proc=proc,
                              env_key=_env_key(runtime_env))
        with self._workers_lock:
            self._workers[worker_id] = handle
        return handle

    BAD_ENV_TTL_S = 60.0

    def rpc_runtime_env_failed(self, conn, send_lock, *, key: str,
                               error: str):
        """A worker died setting up its runtime env (e.g. pip install
        failure): fail every queued task with that env NOW and stop
        respawning workers for it for a while — otherwise the queue
        drives an infinite spawn/install/crash loop with the real error
        trapped in worker stderr."""
        from ray_tpu.runtime_env import env_key as _env_key
        from ray_tpu.utils import exceptions as exc

        self._bad_envs[key] = (error, time.monotonic())
        doomed = []
        with self._ready_cv:
            keep = deque()
            while self._ready:
                task = self._ready.popleft()
                if _env_key(task.get("runtime_env")) == key:
                    doomed.append(task)
                else:
                    keep.append(task)
            self._ready = keep
        for task in doomed:
            self._store_task_error(task, exc.RuntimeEnvSetupError(
                f"runtime env setup failed: {error}"))
        return {"failed_tasks": len(doomed)}

    def _bad_env_error(self, runtime_env) -> str | None:
        from ray_tpu.runtime_env import env_key as _env_key

        hit = self._bad_envs.get(_env_key(runtime_env))
        if hit is None:
            return None
        error, at = hit
        if time.monotonic() - at > self.BAD_ENV_TTL_S:
            return None   # stale: the env may be fixable (cache purged)
        return error

    def rpc_register_worker(self, conn, send_lock, *, worker_id,
                            push_addr=None):
        """Registration handshake; the connection becomes the raylet→worker
        task channel and worker→raylet completion stream."""
        with self._workers_lock:
            handle = self._workers.get(worker_id)
            if handle is None:   # externally started worker (tests)
                handle = WorkerHandle(worker_id=worker_id)
                self._workers[worker_id] = handle
            if push_addr is not None:
                handle.push_addr = tuple(push_addr)
        # the registration ack MUST be the channel's first message: only
        # AFTER it is on the wire may other threads see handle.conn —
        # an actor-delivery thread polling for the conn could otherwise
        # inject create_actor ahead of the ack and fail the handshake
        send_msg(conn, {"registered": True}, send_lock)
        with self._workers_lock:
            handle.conn = conn
            handle.send_lock = send_lock
            if handle.state == "starting":
                # actor-designated workers keep their "actor" state — the
                # dispatcher must never hand them normal tasks
                handle.state = "idle"
        self._kick_dispatch()
        try:
            while not self._stopping:
                try:
                    msg = recv_msg(conn)
                except (OSError, EOFError, Exception):
                    break
                self._on_worker_msg(handle, msg)
        finally:
            self.release_conn(conn)   # held channel finished
            self._on_worker_gone(handle)
        return RpcServer.HELD

    def _on_worker_msg(self, w: WorkerHandle, msg: dict):
        kind = msg.get("type")
        if kind == "task_done":
            self._finish_task(w, msg)
        elif kind == "actor_ready":
            with self._gcs_lock:
                self._gcs.call(
                    "actor_ready", actor_id=msg["actor_id"],
                    node_id=self.node_id,
                    push_addr=(list(w.push_addr) if w.push_addr else None))
        elif kind == "actor_creation_failed":
            with self._gcs_lock:
                self._gcs.call("actor_failed", actor_id=msg["actor_id"],
                               reason=msg.get("reason", "creation failed"))

    def _finish_task(self, w: WorkerHandle, msg: dict):
        with self._workers_lock:
            w.current_task = None
        if w.state == "busy":
            # actor workers keep their acquisition for their LIFETIME
            # (released on death/kill); only per-task resources return here
            self._release(w.acquired)
            w.acquired = {}
            w.state = "idle"
        self._kick_dispatch()

    def _on_worker_gone(self, w: WorkerHandle):
        """Worker process/channel died (reference: NodeManager worker failure
        path — in-flight task gets retried or an error object)."""
        if self._stopping:
            return
        with self._workers_lock:
            if w.state == "dead":
                return  # channel reader and monitor both report deaths
            prior_state = w.state
            w.state = "dead"
            self._workers.pop(w.worker_id, None)
            self._death_info[w.worker_id] = {"oom_killed": w.oom_killed}
            while len(self._death_info) > 256:
                self._death_info.pop(next(iter(self._death_info)))
        # reclaim created-but-unsealed allocations and pinned read refs of
        # the dead worker only (live writers/readers are untouched)
        if w.proc is not None and w.proc.pid:
            self.store.evict_orphans(w.proc.pid)
            self.store.release_pid(w.proc.pid)
        task = w.current_task
        self._release(w.acquired)
        w.acquired = {}
        if prior_state == "actor" and w.actor_id is not None:
            try:
                with self._gcs_lock:
                    self._gcs.call(
                        "actor_failed", actor_id=w.actor_id,
                        reason=f"actor worker {w.worker_id[:8]} died")
            except Exception:  # noqa: BLE001 - gcs may be shutting down
                pass
        elif task is not None:
            decided = all(self.store.contains(bytes.fromhex(o))
                          for o in task.get("return_oids", ()))
            if decided or task.get("cancelled"):
                pass   # cancelled (error pre-stored) or results written:
                       # a retry would re-run completed/cancelled work
            elif w.oom_killed:
                # OOM kills have their OWN budget (config task_oom_retries,
                # reference RAY_task_oom_retries): host pressure from an
                # unrelated process must not burn the task's max_retries
                # lineage budget, and re-dispatch backs off so a
                # still-pressured node doesn't churn through the budget in
                # a few monitor ticks.
                from ray_tpu.utils.config import get_config

                total = get_config().task_oom_retries
                left = task.get("_oom_retries_left", total)
                if left > 0:
                    task["_oom_retries_left"] = left - 1
                    delay = min(8.0, 1.0 * 2 ** (total - left))
                    self._defer_enqueue(task, delay)
                else:
                    from ray_tpu.utils import exceptions as exc
                    self._store_task_error(task, exc.OutOfMemoryError(
                        f"task {task.get('name')}: worker killed to relieve "
                        f"host memory pressure (threshold "
                        f"{self._mem_threshold}; {total} OOM retries "
                        f"exhausted)"))
            elif task.get("max_retries", 0) > 0:
                task["max_retries"] -= 1
                self._enqueue(task)
            else:
                self._store_task_error(
                    task, RuntimeError(
                        f"worker died executing {task.get('name')}"))

    def _store_task_error(self, task: dict, error: BaseException):
        from ray_tpu.utils import exceptions as exc
        err = (error if isinstance(error, exc.RayTpuError)
               else exc.WorkerCrashedError(str(error)))
        for oid_hex in task.get("return_oids", ()):
            oid = bytes.fromhex(oid_hex)
            if not self.store.contains(oid):
                try:
                    # hold through seal→pin: the error object must not be
                    # evictable before the pin (same protocol as worker
                    # returns)
                    size = object_codec.put_value_durable(
                        self.store, oid, err, is_error=True, hold=True,
                        timeout_s=5.0,
                        request_space=(self._spill_bytes
                                       if self._spill_enabled else None))
                except Exception:  # noqa: BLE001 - already created etc.
                    continue
                self._pin_object(oid_hex)
                self._track_local(oid_hex)
                if size > 0:
                    self.store.release(oid)
                with self._gcs_lock:
                    self._gcs.call("add_object_location", oid=oid_hex,
                                   node_id=self.node_id, size=size)

    # ------------------------------------------------------------------
    # scheduling (reference: ClusterTaskManager::QueueAndScheduleTask +
    # LocalTaskManager dispatch; spillback via GCS view)
    # ------------------------------------------------------------------

    def rpc_submit_task(self, conn, send_lock, *, task: dict,
                        spill_count: int = 0):
        demand = task.get("resources", {})
        strategy = task.get("strategy", {})
        if strategy.get("kind") == "NODE_AFFINITY":
            target = strategy.get("node_id")
            if target and target != self.node_id:
                if self._forward(task, target, spill_count):
                    return {"ok": True, "node_id": target}
        if strategy.get("pg_id") and spill_count == 0:
            # placement-group tasks run on the bundle's reserved node
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        pg_id=strategy["pg_id"])
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count + 1):
                    return {"ok": True, "node_id": target}
        if not _fits(demand, self.total_resources) or (
                strategy.get("kind") == "SPREAD" and spill_count == 0):
            # infeasible here (or spread): ask GCS for a placement
            with self._gcs_lock:
                target = self._gcs.call(
                    "pick_node", demand=demand,
                    exclude=[] if _fits(demand, self.total_resources)
                    else [self.node_id],
                    pg_id=strategy.get("pg_id"))
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count):
                    return {"ok": True, "node_id": target}
            if not _fits(demand, self.total_resources):
                if (strategy.get("pg_id")
                        or strategy.get("kind") == "NODE_AFFINITY"):
                    # strategy-constrained tasks cannot be re-placed by
                    # the plain-demand retry loop (it would escape the PG
                    # reservation / ping-pong on affinity) — keep the
                    # immediate infeasible error for them
                    self._store_task_error(task, ValueError(
                        f"task {task.get('name')} demands {demand}: "
                        f"infeasible for its placement constraint"))
                    return {"ok": False, "reason": "infeasible"}
                # Cluster-wide infeasible: PARK the task and advertise the
                # unmet demand so the autoscaler can provision for it
                # (reference: infeasible queue feeding
                # GcsAutoscalerStateManager). Errors only after the grace
                # window — a fixed cluster still fails fast enough.
                self._park_infeasible(task, demand)
                return {"ok": True, "parked": "infeasible"}
        elif spill_count < 2 and not _fits(demand, self._avail_snapshot()):
            # busy here: one spillback attempt through the GCS view
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        exclude=[self.node_id],
                                        pg_id=strategy.get("pg_id"))
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count + 1):
                    return {"ok": True, "node_id": target}
        self._enqueue(task)
        return {"ok": True, "node_id": self.node_id}

    def _forward(self, task: dict, node_id: str, spill_count: int) -> bool:
        peer = self._peer(node_id)
        if peer is None:
            return False
        try:
            peer.call("submit_task", task=task, spill_count=spill_count + 1)
            return True
        except Exception:  # noqa: BLE001 - peer died; fall back local
            return False

    def _peer(self, node_id: str) -> RpcClient | None:
        with self._peers_lock:
            client = self._peers.get(node_id)
            if client is not None and client._closed:
                # connection died (peer restarted/stopped): re-resolve
                self._peers.pop(node_id, None)
                self._peer_addrs.pop(node_id, None)
                client = None
        if client is not None:
            return client
        with self._gcs_lock:
            nodes = self._gcs.call("get_nodes", alive_only=True)
        for n in nodes:
            if n["node_id"] == node_id:
                try:
                    client = RpcClient(n["address"])
                except OSError:
                    return None
                with self._peers_lock:
                    self._peers[node_id] = client
                    self._peer_addrs[node_id] = tuple(n["address"])
                return client
        return None

    def _enqueue(self, task: dict):
        with self._ready_cv:
            self._ready.append(task)
            self._ready_cv.notify()

    def _defer_enqueue(self, task: dict, delay: float):
        """Re-enqueue after a delay (OOM backoff). Timers are tracked so
        stop() cancels them — an untracked timer firing after the store
        closes would enqueue into a dead dispatch loop; the task is then
        lost like any other task queued on a stopping node (cluster-level
        recovery owns that case)."""
        timer = threading.Timer(delay, self._timer_enqueue, args=(task,))
        timer.daemon = True
        with self._timers_lock:
            if self._stopping:
                return
            self._deferred_timers.add(timer)
        timer.start()

    def _timer_enqueue(self, task: dict):
        with self._timers_lock:
            self._deferred_timers = {t for t in self._deferred_timers
                                     if t.is_alive()}
        if not self._stopping:
            self._enqueue(task)

    def _kick_dispatch(self):
        with self._ready_cv:
            self._dispatch_gen += 1
            self._ready_cv.notify()

    def _avail_snapshot(self) -> dict:
        with self._res_lock:
            return dict(self.available)

    def _try_acquire(self, demand: dict) -> bool:
        with self._res_lock:
            if not _fits(demand, self.available):
                return False
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True

    def _release(self, demand: dict):
        if not demand:
            return
        with self._res_lock:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
        # freed capacity may unblock a parked lease request or queued task
        self._kick_dispatch()

    def _dispatch_loop(self):
        while not self._stopping:
            with self._ready_cv:
                while (not self._ready and not self._lease_waiters
                       and not self._stopping):
                    self._ready_cv.wait(timeout=0.2)
                if self._stopping:
                    return
                gen0 = self._dispatch_gen
                task = None
                # first task whose resources fit (avoid head-of-line block)
                for i, t in enumerate(self._ready):
                    if _fits(t.get("resources", {}), self._avail_snapshot()):
                        task = t
                        del self._ready[i]
                        break
            self._serve_lease_waiters()
            if task is None:
                # only lease waiters, or no fitting task: block until the
                # next kick (completion/registration/release)
                with self._ready_cv:
                    if self._dispatch_gen == gen0 and not self._stopping:
                        self._ready_cv.wait(timeout=0.1)
                continue
            env_err = self._bad_env_error(task.get("runtime_env"))
            if env_err is not None:
                from ray_tpu.utils import exceptions as exc
                self._store_task_error(task, exc.RuntimeEnvSetupError(
                    f"runtime env setup failed: {env_err}"))
                continue
            gen = self._dispatch_gen
            worker = self._idle_worker(task.get("runtime_env"))
            if worker is None:
                self._enqueue(task)
                # wait for a completion/registration kick instead of a
                # fixed sleep: task_done latency, not a poll, sets the
                # dispatch rate when all workers are busy. The generation
                # check under the cv closes the missed-wakeup race (a
                # kick between the snapshot above and this wait).
                with self._ready_cv:
                    if self._dispatch_gen == gen and not self._stopping:
                        self._ready_cv.wait(timeout=0.2)
                continue
            if not self._try_acquire(task.get("resources", {})):
                worker.state = "idle"
                self._enqueue(task)
                continue
            cancelled = False
            with self._workers_lock:
                # under the lock: cancel_task scans current_task here, and
                # a cancel that ran between the queue pop and this point
                # left a flag on the task dict
                if task.get("cancelled"):
                    cancelled = True
                    worker.state = "idle"
                else:
                    worker.acquired = dict(task.get("resources", {}))
                    worker.current_task = task
                    worker.dispatched_at = time.monotonic()
            if cancelled:
                # outside _workers_lock: _release kicks the dispatch cv,
                # and holding the worker lock across that inverts the
                # cv→workers lock order used by the lease grant path
                self._release(task.get("resources", {}))
                continue
            try:
                send_msg(worker.conn, {"type": "task", "task": task},
                         worker.send_lock)
            except OSError:
                self._on_worker_gone(worker)
                self._enqueue(task)

    def _idle_worker(self, runtime_env: dict | None = None
                     ) -> WorkerHandle | None:
        """Grab an idle registered worker WITH a matching runtime-env
        key; spawn one for this env when under the cap. At the cap, an
        idle worker with a DIFFERENT env key is evicted to make room —
        otherwise a full pool of mismatched-env workers starves the task
        forever (reference: worker_pool.cc kills idle workers beyond the
        cached-soft-limit when a lease needs a different runtime_env)."""
        from ray_tpu.runtime_env import env_key as _env_key

        key = _env_key(runtime_env)
        evict = None
        with self._workers_lock:
            n_alive = 0
            incoming = False  # replacement with this env already booting?
            for w in self._workers.values():
                if w.state in ("idle", "busy", "starting", "actor",
                               "leased"):
                    n_alive += 1
                if w.state == "starting" and w.env_key == key:
                    incoming = True
                if (w.state == "idle" and w.conn is not None
                        and w.env_key == key):
                    w.state = "busy"
                    return w
            if incoming:
                # a matching worker is already on its way — evicting more
                # warm workers per dispatch retry would drain the whole
                # pool for one task
                return None
            spawn = n_alive < self._max_workers
            if not spawn:
                for w in self._workers.values():
                    if (w.state == "idle" and w.conn is not None
                            and w.env_key != key):
                        # not "dead": _on_worker_gone must still run its
                        # cleanup (pop from registry, store refs, zombie
                        # reap) when the channel closes
                        w.state = "evicting"
                        evict = w
                        spawn = True
                        break
        if evict is not None:
            # off the dispatch thread: a worker slow to honor SIGTERM
            # must not stall dispatch for every other queued task
            def _reap(w=evict):
                try:
                    if w.proc is not None:
                        w.proc.terminate()
                    if w.conn is not None:
                        w.conn.close()
                except OSError:
                    pass
                self._on_worker_gone(w)
                if w.proc is not None:
                    try:
                        w.proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        w.proc.kill()

            threading.Thread(target=_reap, name="ray_tpu-evict",
                             daemon=True).start()
        if spawn:
            self._spawn_worker(runtime_env)
        return None

    # ------------------------------------------------------------------
    # actors (GCS calls host_actor; raylet dedicates a worker)
    # ------------------------------------------------------------------

    def rpc_host_actor(self, conn, send_lock, *, actor_id, spec,
                       incarnation=0):
        """Dedicate a fresh worker to the actor and hand it the creation
        task (reference: GcsActorScheduler::LeaseWorkerFromNode + the
        worker-lease machinery in node_manager.cc:1778)."""
        demand = spec.get("resources", {})
        if not self._try_acquire(demand):
            raise RuntimeError(
                f"node {self.node_id} cannot host actor: {demand} unavailable")
        handle = self._spawn_worker(spec.get("runtime_env"))
        handle.state = "actor"
        handle.actor_id = actor_id
        handle.incarnation = incarnation
        handle.acquired = dict(demand)

        def _deliver():
            # pip envs legitimately take minutes on a cold cache: give
            # the worker's registration the install window, not 30s
            renv = (spec.get("runtime_env") or {})
            deadline = time.monotonic() + (900 if renv.get("pip") else 30)
            while time.monotonic() < deadline and not self._stopping:
                if handle.conn is not None:
                    try:
                        send_msg(handle.conn,
                                 {"type": "create_actor", "actor_id": actor_id,
                                  "task": spec,
                                  "incarnation": incarnation},
                                 handle.send_lock)
                    except OSError:
                        self._on_worker_gone(handle)
                    return
                if handle.proc is not None and handle.proc.poll() is not None:
                    break
                time.sleep(0.01)
            with self._gcs_lock:
                self._gcs.call("actor_failed", actor_id=actor_id,
                               reason="actor worker failed to register")
        threading.Thread(target=_deliver, daemon=True).start()
        return {"ok": True}

    def rpc_submit_actor_task(self, conn, send_lock, *, task: dict):
        actor_id = task["actor_id"]
        with self._workers_lock:
            target = None
            for w in self._workers.values():
                if w.actor_id == actor_id and w.state == "actor":
                    target = w
                    break
        if target is None or target.conn is None:
            raise LookupError(f"actor {actor_id} not hosted here")
        if task.get("incarnation", 0) != target.incarnation:
            # caller's seq numbering belongs to a previous incarnation —
            # reject so it refreshes (reference: client resend protocol)
            raise LookupError(
                f"actor {actor_id} incarnation mismatch "
                f"(task {task.get('incarnation')} != {target.incarnation})")
        send_msg(target.conn, {"type": "actor_task", "task": task},
                 target.send_lock)
        return {"ok": True}

    def rpc_free_objects(self, conn, send_lock, *, oids: list,
                         broadcast: bool = True):
        """Explicitly release object copies on this node (reference:
        ``ray.internal.free``): unpin, drop from shm and the spill dir,
        deregister the location. Owners drop lineage separately so a
        subsequent ``get`` raises ObjectLostError instead of
        resurrecting the object."""
        from ray_tpu._private.shm_store import TS_ERR, TS_OK

        freed = 0
        pending: list[tuple[str, bool, bool]] = []  # (oid, was_pinned, spilled)
        for oid_hex in oids:
            with self._pin_lock:
                was_pinned = oid_hex in self._pinned
            self._unpin_object(oid_hex)
            with self._spill_lock:
                entry = self._spilled.pop(oid_hex, None)
            if entry is not None:
                try:
                    os.unlink(entry[0])
                except OSError:
                    pass
                freed += 1
            pending.append((oid_hex, was_pinned, entry is not None))
        # drain in-flight refs (a writer's seal-hold released right after
        # its report RPC, or a reader mid-get) with ONE shared ~200ms
        # budget across all oids, not per object
        done: list[tuple[str, bool, int]] = []
        deadline = time.monotonic() + 0.2
        while pending:
            still = []
            for oid_hex, was_pinned, had_spill in pending:
                rc = self.store.try_delete(bytes.fromhex(oid_hex))
                if rc == TS_ERR and time.monotonic() < deadline:
                    still.append((oid_hex, was_pinned, had_spill))
                else:
                    done.append((oid_hex, had_spill, rc))
                    if rc == TS_ERR and was_pinned:
                        # a reader outlived the drain: the surviving
                        # primary stays authoritative — re-pin it so LRU
                        # eviction cannot silently orphan the stale GCS
                        # location (same rule as _spill_one)
                        self._pin_object(oid_hex)
            pending = still
            if pending:
                time.sleep(0.01)
        for oid_hex, had_spill, rc in done:
            if rc == TS_OK and not had_spill:
                freed += 1
            if rc == TS_ERR:
                continue   # copy stays: tracked, registered, re-pinned
            with self._local_objects_lock:
                was_local = oid_hex in self._local_objects
                self._local_objects.discard(oid_hex)
            if was_local or had_spill:
                try:
                    with self._gcs_lock:
                        self._gcs.call("remove_object_location",
                                       oid=oid_hex, node_id=self.node_id)
                except Exception:  # noqa: BLE001 - best-effort
                    pass
        if broadcast:
            with self._gcs_lock:
                nodes = self._gcs.call("get_nodes", alive_only=True)
            for n in nodes:
                if n["node_id"] == self.node_id:
                    continue
                peer = self._peer(n["node_id"])
                if peer is None:
                    continue
                try:
                    peer.call("free_objects", oids=list(oids),
                              broadcast=False)
                except Exception:  # noqa: BLE001 - peer gone
                    continue
        return {"freed": freed}

    def rpc_cancel_task(self, conn, send_lock, *, oids: list,
                        force: bool = False, broadcast: bool = True):
        """Cancel the task owning these return oids (reference:
        ``CoreWorker::CancelTask`` → raylet CancelTask RPC): queued tasks
        are dequeued; a running task's worker gets SIGINT (``force``:
        SIGKILL). The TaskCancelledError return object is written FIRST —
        first-write-wins makes a racing normal completion a no-op.
        Already-finished tasks (return objects exist) are untouched."""
        from ray_tpu.utils import exceptions as exc

        targets = set(oids)
        if all(self.store.contains(bytes.fromhex(o)) for o in targets):
            return {"found": True, "state": "finished"}

        def matches(task):
            return task and targets & set(task.get("return_oids", ()))

        # queued here? Flag + dequeue under the cv; the error store (a
        # durable put + GCS RPC) runs OUTSIDE the cv so dispatch/enqueue
        # never stall behind it. The flag also covers a task already
        # popped by the dispatch loop but not yet assigned to a worker.
        queued = None
        with self._ready_cv:
            for i, t in enumerate(self._ready):
                if matches(t):
                    queued = t
                    del self._ready[i]
                    break
        if queued is not None:
            queued["cancelled"] = True
            self._store_task_error(queued, exc.TaskCancelledError(
                f"task {queued.get('name')} cancelled while queued"))
            return {"found": True, "state": "queued"}
        # running here?
        with self._workers_lock:
            victim = None
            task = None
            for w in self._workers.values():
                if w.state == "busy" and matches(w.current_task):
                    victim = w
                    task = w.current_task   # captured under the lock
                    task["cancelled"] = True
                    break
        if victim is not None:
            # pre-store the cancelled error; the worker's own
            # (interrupted or successful) write loses the race. Known
            # best-effort window for MULTI-return tasks: if the worker is
            # concurrently writing its returns, the task can complete with
            # a mix of real values and TaskCancelledError across the
            # return set (each oid resolves first-write-wins
            # independently). Cancel is best-effort by contract — callers
            # must treat any TaskCancelledError among the returns as "the
            # task may have partially run".
            self._store_task_error(task, exc.TaskCancelledError(
                f"task {task.get('name')} cancelled while running"))
            with self._workers_lock:
                # re-verify AND signal under the lock: the worker may
                # have finished the target and been handed new work —
                # never deliver the kill/interrupt over someone else's
                # task (_finish_task and dispatch both mutate
                # current_task under this lock)
                if victim.current_task is not task:
                    return {"found": True, "state": "running"}
                if force:
                    # no retry for a cancelled task: detach it first
                    victim.current_task = None
                    if victim.proc is not None:
                        try:
                            victim.proc.kill()
                        except OSError:
                            pass
                elif victim.proc is not None:
                    import signal

                    try:
                        victim.proc.send_signal(signal.SIGINT)
                    except OSError:
                        pass
            return {"found": True, "state": "running"}
        # parked infeasible here? (pop under the lock; the durable error
        # store runs outside it — _park_infeasible on the submit path
        # contends for this lock)
        parked = None
        with self._infeasible_lock:
            for i, (t, _, _) in enumerate(self._infeasible):
                if matches(t):
                    parked = self._infeasible.pop(i)[0]
                    break
        if parked is not None:
            parked["cancelled"] = True
            self._store_task_error(parked, exc.TaskCancelledError(
                f"task {parked.get('name')} cancelled while infeasible"))
            return {"found": True, "state": "infeasible"}
        if broadcast:
            with self._gcs_lock:
                nodes = self._gcs.call("get_nodes", alive_only=True)
            for n in nodes:
                if n["node_id"] == self.node_id:
                    continue
                peer = self._peer(n["node_id"])
                if peer is None:
                    continue
                try:
                    reply = peer.call("cancel_task", oids=list(oids),
                                      force=force, broadcast=False)
                    if reply.get("found"):
                        return reply
                except Exception:  # noqa: BLE001 - peer gone
                    continue
        return {"found": False}

    def rpc_kill_actor_worker(self, conn, send_lock, *, actor_id):
        with self._workers_lock:
            target = None
            for w in self._workers.values():
                if w.actor_id == actor_id:
                    target = w
                    break
        if target is not None and target.proc is not None:
            target.proc.terminate()
        return {"ok": True}

    # ------------------------------------------------------------------
    # object spilling (reference: LocalObjectManager + ExternalStorage —
    # spill LRU-cold objects to files under memory pressure, restore on
    # read; the GCS object directory keeps this node as a location)
    # ------------------------------------------------------------------

    def _track_local(self, oid_hex: str):
        with self._local_objects_lock:
            self._local_objects.add(oid_hex)
        # wake ensure_local waiters (event-driven instead of polling for
        # the locally-produced-object case)
        with self._local_cv:
            self._local_cv.notify_all()

    def _reconcile_locations(self):
        """Deregister objects that silently left the store (LRU-evicted
        secondaries): a stale directory entry would make owners pull from
        a node that cannot serve, and would mask true object loss from
        the lineage-reconstruction path."""
        with self._local_objects_lock:
            snapshot = list(self._local_objects)
        gone = []
        for oid_hex in snapshot:
            # _spilled FIRST, store second: a concurrent restore pops
            # _spilled only AFTER the shm copy is secured+pinned, so this
            # order can never classify a mid-restore object as gone
            # (store-first could: miss the store, then miss _spilled
            # right after the restore completed)
            with self._spill_lock:
                if oid_hex in self._spilled:
                    continue   # spilled = still servable from disk
            if self.store.contains(bytes.fromhex(oid_hex)):
                continue
            gone.append(oid_hex)
        if not gone:
            return
        with self._local_objects_lock:
            self._local_objects.difference_update(gone)
        with self._pin_lock:
            self._pinned.difference_update(gone)
        for oid_hex in gone:
            try:
                with self._gcs_lock:
                    self._gcs.call("remove_object_location", oid=oid_hex,
                                   node_id=self.node_id)
            except Exception:  # noqa: BLE001 - gcs down; retried next tick
                with self._local_objects_lock:
                    self._local_objects.add(oid_hex)

    def _pin_object(self, oid_hex: str):
        """Pin a newly created primary copy (idempotent)."""
        with self._pin_lock:
            if oid_hex in self._pinned:
                return
            if self.store.pin(bytes.fromhex(oid_hex)):
                self._pinned.add(oid_hex)

    def _unpin_object(self, oid_hex: str):
        with self._pin_lock:
            if oid_hex in self._pinned:
                self._pinned.discard(oid_hex)
                self.store.unpin(bytes.fromhex(oid_hex))

    def rpc_report_object(self, conn, send_lock, *, oid: str, size: int = 0):
        """A local process created an object: pin the primary copy and
        register the location with the GCS (reference: the Put path's
        PinObjectIDs + object directory update). Callers seal with a held
        ref (``seal(hold=True)``) so the object cannot vanish before the
        pin lands here.

        The PIN is synchronous (it is what makes the object durable); the
        GCS directory registration is BUFFERED and flushed in batches —
        one directory RPC per flush, not per task return, keeping the
        head-node round trip off the task hot path (reference: the
        ownership-based object directory is similarly not on the task
        completion critical path)."""
        self._pin_object(oid)
        with self._pin_lock:
            pinned = oid in self._pinned
        if not pinned and not self.store.contains(bytes.fromhex(oid)):
            # should be unreachable under the hold protocol; never
            # advertise a location that cannot serve the object
            return {"ok": False, "reason": "object not present to pin"}
        self._track_local(oid)
        self._queue_location(oid, size)
        return {"ok": True}

    def rpc_report_objects(self, conn, send_lock, *, entries: list):
        """Batched report_object (workers buffer their task-return
        reports and flush together; each object is protected by its
        writer's seal-hold until the pin lands here)."""
        ok = []
        for oid, size in entries:
            self._pin_object(oid)
            with self._pin_lock:
                pinned = oid in self._pinned
            if pinned or self.store.contains(bytes.fromhex(oid)):
                self._track_local(oid)
                self._queue_location(oid, size)
                ok.append(oid)
        return {"ok": ok}

    def _queue_location(self, oid: str, size: int):
        with self._loc_cv:
            self._loc_buf.append((oid, size))
            self._loc_cv.notify()

    def _location_flush_loop(self):
        """Drain the location buffer into batched GCS registrations. A
        short linger coalesces bursts; an empty buffer blocks on the cv
        (no polling)."""
        while not self._stopping:
            with self._loc_cv:
                if not self._loc_buf:
                    self._loc_cv.wait(timeout=0.2)
                if not self._loc_buf:
                    continue
                time_to_linger = 0.002
            time.sleep(time_to_linger)  # let the burst accumulate
            with self._loc_cv:
                batch, self._loc_buf = self._loc_buf, []
            if not batch:
                continue
            try:
                with self._gcs_lock:
                    self._gcs.call("add_object_locations",
                                   node_id=self.node_id, entries=batch)
            except Exception:  # noqa: BLE001 - GCS down; heartbeat
                pass           # reconciliation re-registers local objects

    def rpc_request_space(self, conn, send_lock, *, nbytes: int = 0):
        """A writer hit store-OOM: synchronously spill pinned-idle objects
        to make room (reference: CreateRequestQueue retry + triggered
        spill). Returns the number of objects spilled."""
        if not self._spill_enabled:
            return {"spilled": 0}  # honor the no-disk-writes contract
        # floor scaled to the allocation (2x for headroom) and the store
        # (1/8 capacity) — a fixed large floor would thrash small stores
        cap = self.store.capacity
        target = min(max(2 * int(nbytes), cap // 8), cap)
        n = self._spill_bytes(target)
        if n == 0:
            # nothing pinned-idle; last resort, spill unpinned cold
            # entries too (they are evictable anyway — spilling keeps
            # them readable instead of destroying them)
            for oid in self.store.spill_candidates(target, pin_pid=0):
                n += bool(self._spill_one(oid[:ObjectID.SIZE]))
        return {"spilled": n}

    def _spill_bytes(self, target: int) -> int:
        n = 0
        for oid in self.store.spill_candidates(target,
                                               pin_pid=os.getpid()):
            n += bool(self._spill_one(oid[:ObjectID.SIZE]))
        return n

    def _spill_loop(self):
        while not self._stopping:
            time.sleep(0.2)
            try:
                st = self.store.stats()
            except Exception:  # noqa: BLE001 - store closing
                return
            cap = st["capacity"] or 1
            if st["bytes_allocated"] <= self._spill_high * cap:
                continue
            self._spill_bytes(
                st["bytes_allocated"] - int(self._spill_low * cap))

    def _spill_one(self, oid: bytes) -> bool:
        """Copy one sealed object out to a file, then drop it from shm."""
        oid_hex = oid.hex()
        try:
            payload = object_codec.raw_bytes(self.store, oid, timeout_ms=0)
        except Exception:  # noqa: BLE001 - vanished (freed/evicted) — fine
            return False
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, oid_hex)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        from ray_tpu._private.shm_store import TS_ERR, TS_OK

        with self._pin_lock:
            was_primary = oid_hex in self._pinned
        with self._spill_lock:
            self._spilled[oid_hex] = (path, was_primary)
        self._unpin_object(oid_hex)
        rc = self.store.try_delete(oid)
        if rc == TS_ERR:
            # a reader still holds a ref: keep the shm copy authoritative —
            # re-pin, discard the file
            self._pin_object(oid_hex)
            with self._spill_lock:
                self._spilled.pop(oid_hex, None)
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        # TS_OK: we removed it. TS_NOT_FOUND: a concurrent evict/spill beat
        # us to it — the file we just wrote may now be the ONLY copy, so it
        # must stay registered either way.
        self.spill_stats["num_spilled"] += 1
        self.spill_stats["bytes_spilled"] += len(payload)
        return rc == TS_OK

    def _restore_spilled(self, oid_hex: str) -> bool:
        """Load a locally-spilled object back into shm (for readers)."""
        with self._spill_lock:
            entry = self._spilled.get(oid_hex)
        if entry is None:
            return False
        path, was_primary = entry
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError:
            with self._spill_lock:
                self._spilled.pop(oid_hex, None)
            return False
        from ray_tpu._private.shm_store import (ObjectExistsError,
                                                StoreFullError)

        oid = bytes.fromhex(oid_hex)
        held = False
        for _ in range(8):
            try:
                # hold through the seal: the restored entry must never sit
                # at refcount 0 where eviction/spill could destroy it
                # before we pin + unlink the file
                object_codec.put_raw(self.store, oid, payload, hold=True)
                held = True
                break
            except ObjectExistsError:
                break  # racing restore won; theirs is pinned
            except StoreFullError:
                # make room by spilling OTHER pinned-idle objects
                if self._spill_bytes(len(payload)) == 0:
                    time.sleep(0.05)  # wait for readers to release
            except Exception:  # noqa: BLE001 - racing restore
                break
        if was_primary:
            self._pin_object(oid_hex)   # restored primary: pin again
        if held:
            self.store.release(oid)
        if was_primary:
            with self._pin_lock:
                ok = oid_hex in self._pinned
        else:
            # secondary: stays unpinned/evictable; success = it is present
            ok = held or self.store.contains(oid)
        if not ok:
            # could not secure the shm copy — the file stays the
            # authoritative copy; do NOT unlink
            return self.store.contains(oid)
        with self._spill_lock:
            self._spilled.pop(oid_hex, None)
        try:
            os.unlink(path)
        except OSError:
            pass
        self.spill_stats["num_restored"] += 1
        self.spill_stats["bytes_restored"] += len(payload)
        return True

    def _read_spilled(self, oid_hex: str) -> bytes | None:
        """Read a spilled object's bytes without restoring it to shm
        (serving a remote fetch should not churn local memory)."""
        with self._spill_lock:
            entry = self._spilled.get(oid_hex)
        if entry is None:
            return None
        try:
            with open(entry[0], "rb") as f:
                return f.read()
        except OSError:
            return None

    # ------------------------------------------------------------------
    # object manager (reference: object_manager.cc Push/HandlePush +
    # PullManager; pull-only here)
    # ------------------------------------------------------------------

    def rpc_fetch_object(self, conn, send_lock, *, oid: str):
        """Return the encoded object bytes from the local store."""
        try:
            return object_codec.raw_bytes(self.store, bytes.fromhex(oid),
                                          timeout_ms=0)
        except ObjectNotFoundError:
            payload = self._read_spilled(oid)
            if payload is None:
                raise
            return payload

    def rpc_fetch_object_meta(self, conn, send_lock, *, oid: str):
        """Size + CRC probe for the pull path (reference: the object
        directory carries sizes for PullManager admission; the checksum
        is transfer integrity — the destination verifies the assembled
        bytes before SEALING, so a torn read can never become a readable
        object). Objects are immutable, so size+CRC memoize per oid —
        repeat probes (N pullers, retries) cost a dict hit, not an
        O(size) pass on the handler thread."""
        import zlib

        cached = self._crc_cache.get(oid)
        if cached is not None:
            return {"found": True, "size": cached[0], "crc32": cached[1]}
        oid_b = bytes.fromhex(oid)
        try:
            view = self.store.get(oid_b, timeout_ms=0)
            try:
                size, crc = view.nbytes, zlib.crc32(view)
            finally:
                view.release()
                self.store.release(oid_b)
        except ObjectNotFoundError:
            data = self._read_spilled(oid)
            if data is None:
                return {"found": False}
            size, crc = len(data), zlib.crc32(data)
        self._crc_cache[oid] = (size, crc)
        while len(self._crc_cache) > 4096:
            self._crc_cache.pop(next(iter(self._crc_cache)))
        return {"found": True, "size": size, "crc32": crc}

    def rpc_fetch_object_chunk(self, conn, send_lock, *, oid: str,
                               offset: int, length: int):
        """One chunk of an object's raw encoding (reference:
        ObjectManager chunked transfer, 5 MiB default chunks —
        object_manager.cc:339). Spilled objects are served by file seek —
        no whole-object restore to answer a remote read."""
        oid_b = bytes.fromhex(oid)
        try:
            view = self.store.get(oid_b, timeout_ms=0)
            try:
                return bytes(view[offset:offset + length])
            finally:
                view.release()
                self.store.release(oid_b)
        except ObjectNotFoundError:
            with self._spill_lock:
                entry = self._spilled.get(oid)
            if entry is None:
                raise
            with open(entry[0], "rb") as f:
                f.seek(offset)
                return f.read(length)

    def rpc_ensure_local(self, conn, send_lock, *, oids: list,
                         timeout_s: float = 30.0):
        """Make objects locally readable, pulling from peers as needed.
        Returns the list of oids that could NOT be made local in time.
        Waits are event-driven for locally-produced objects (the common
        case): report_object notifies ``_local_cv``."""
        deadline = time.monotonic() + timeout_s
        missing = [o for o in oids
                   if not self.store.contains(bytes.fromhex(o))]
        while missing and time.monotonic() < deadline:
            still = []
            for oid_hex in missing:
                oid = bytes.fromhex(oid_hex)
                if self.store.contains(oid):
                    continue
                if not self._pull(oid_hex):
                    still.append(oid_hex)
            missing = still
            if missing:
                # wake instantly when a local task seals one of ours;
                # re-check remote locations on a coarser cadence
                with self._local_cv:
                    self._local_cv.wait(
                        timeout=min(0.1, max(deadline - time.monotonic(),
                                             0.0)))
        return missing

    def _peer_addresses_for(self, oid_hex: str) -> list:
        with self._gcs_lock:
            locs = self._gcs.call("get_object_locations",
                                  oids=[oid_hex])[oid_hex]
        out = []
        for node_id in locs:
            if node_id == self.node_id:
                continue
            addr = self._peer_address(node_id)
            if addr is not None:
                out.append((node_id, addr))
        return out

    def _on_pulled(self, oid_hex: str, size: int):
        self._track_local(oid_hex)
        self._queue_location(oid_hex, size)

    def _pull(self, oid_hex: str) -> bool:
        return self._pulls.pull(oid_hex)

    # ------------------------------------------------------------------
    # worker leases (owner-side lease protocol; reference:
    # NodeManager::HandleRequestWorkerLease node_manager.cc:1778 +
    # CoreWorkerDirectTaskSubmitter direct_task_transport.cc:134,240)
    # ------------------------------------------------------------------

    def _peer_address(self, node_id) -> tuple | None:
        if node_id is None or node_id == self.node_id:
            return None
        if self._peer(node_id) is None:
            return None
        with self._peers_lock:
            return self._peer_addrs.get(node_id)

    def rpc_request_lease(self, conn, send_lock, *, demand: dict,
                          runtime_env: dict | None = None,
                          timeout_s: float = 10.0, spill_count: int = 0):
        """Grant a worker lease: the reply carries the worker's push
        address, and the owner pushes tasks to it directly for as long as
        it holds the lease (= keeps its connection to the worker open).
        Replies: {ok, worker_addr, worker_id, node_id} | {redirect: addr}
        (spillback — caller retries there) | {retry: True} (parked past
        timeout_s — caller may re-request) | {infeasible: True}."""
        if not _fits(demand, self.total_resources):
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        exclude=[self.node_id])
            addr = self._peer_address(target)
            if addr:
                return {"redirect": list(addr), "node_id": target}
            return {"infeasible": True}
        if spill_count < 1 and not _fits(demand, self._avail_snapshot()):
            # busy here: one spillback attempt through the GCS view
            # (mirror of rpc_submit_task's policy)
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        exclude=[self.node_id])
            addr = self._peer_address(target)
            if addr:
                return {"redirect": list(addr), "node_id": target}
        waiter = {"demand": demand, "runtime_env": runtime_env,
                  "event": threading.Event(), "result": None}
        with self._ready_cv:
            self._lease_waiters.append(waiter)
            self._ready_cv.notify()
        if not waiter["event"].wait(timeout=timeout_s):
            removed = True
            with self._ready_cv:
                try:
                    self._lease_waiters.remove(waiter)
                except ValueError:
                    removed = False
            if not removed:
                # a granter claimed the waiter concurrently: it WILL set
                # the result (it already holds the worker + resources) —
                # block for it; dropping it would leak a leased worker
                # nobody ever dials
                waiter["event"].wait(timeout=5.0)
                if waiter["result"]:
                    return waiter["result"]
            return {"retry": True}
        return waiter["result"]

    def _serve_lease_waiters(self):
        """Grant parked lease requests FIFO while workers + resources are
        available (runs on the dispatch thread)."""
        while True:
            with self._ready_cv:
                if not self._lease_waiters:
                    return
                waiter = self._lease_waiters[0]
            env_err = self._bad_env_error(waiter["runtime_env"])
            if env_err is not None:
                with self._ready_cv:
                    try:
                        self._lease_waiters.remove(waiter)
                    except ValueError:
                        continue
                waiter["result"] = {"infeasible": True,
                                    "env_error": env_err}
                waiter["event"].set()
                continue
            worker = self._idle_worker(waiter["runtime_env"])
            if worker is None:
                return  # spawn in progress / pool exhausted; kick revisits
            if worker.push_addr is None:
                # externally-registered worker with no push port (tests):
                # unusable for leases, put it back
                with self._workers_lock:
                    worker.state = "idle"
                return
            if not self._try_acquire(waiter["demand"]):
                with self._workers_lock:
                    worker.state = "idle"
                return  # resources busy; release kick revisits
            # the waiter may have timed out and removed itself while we
            # were acquiring — then the grant must be rolled back. The
            # rollback runs OUTSIDE the cv (lock order: never cv→locks).
            claimed = True
            with self._ready_cv:
                try:
                    self._lease_waiters.remove(waiter)
                except ValueError:
                    claimed = False
            if not claimed:
                self._release(waiter["demand"])
                with self._workers_lock:
                    worker.state = "idle"
                continue
            with self._workers_lock:
                worker.state = "leased"
                worker.acquired = dict(waiter["demand"])
                worker.dispatched_at = time.monotonic()
            # arm the worker's never-dialed watchdog BEFORE the owner can
            # learn the address (guarantees msg-before-dial ordering)
            try:
                send_msg(worker.conn, {"type": "lease_granted"},
                         worker.send_lock)
            except OSError:
                pass
            waiter["result"] = {"ok": True,
                                "worker_addr": list(worker.push_addr),
                                "worker_id": worker.worker_id,
                                "node_id": self.node_id}
            waiter["event"].set()

    def rpc_cancel_leased(self, conn, send_lock, *, worker_id: str,
                          task: dict, force: bool = False):
        """Cancel a task running on a LEASED worker. The owner (who alone
        knows what its lease is executing) names the worker and supplies
        the task's return oids; this raylet pre-stores the cancel error
        and interrupts (SIGINT) or kills the worker process."""
        from ray_tpu.utils import exceptions as exc

        with self._workers_lock:
            w = self._workers.get(worker_id)
            if w is None or w.state != "leased" or w.proc is None:
                return {"found": False}
        task["cancelled"] = True
        self._store_task_error(task, exc.TaskCancelledError(
            f"task {task.get('name')} cancelled while running"))
        with self._workers_lock:
            w = self._workers.get(worker_id)
            if w is None or w.state != "leased" or w.proc is None:
                return {"found": False}
            try:
                if force:
                    w.proc.kill()
                elif w.conn is not None:
                    # targeted: the worker interrupts the task BY ID
                    # (a raw SIGINT could hit a batchmate in a grouped
                    # push)
                    send_msg(w.conn, {"type": "cancel_push",
                                      "task_id": task.get("task_id", "")},
                             w.send_lock)
            except OSError:
                pass
        return {"found": True}

    def rpc_worker_death_info(self, conn, send_lock, *, worker_id: str,
                              timeout_s: float = 2.0):
        """Why a worker died (lease owners map a broken lease to e.g.
        OutOfMemoryError instead of a generic crash). The owner's lease
        connection breaks the instant the process dies — often BEFORE
        this raylet's channel reader records the death — so this briefly
        waits for the record instead of returning an empty answer."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._workers_lock:
                info = self._death_info.get(worker_id)
            if info is not None:
                return info
            if time.monotonic() >= deadline or self._stopping:
                return {}
            time.sleep(0.05)

    def rpc_lease_closed(self, conn, send_lock, *, worker_id: str):
        """The worker's owner-facing connection dropped (lease returned or
        owner died): the worker and its resources go back to the pool."""
        with self._workers_lock:
            w = self._workers.get(worker_id)
            if w is None or w.state != "leased":
                return {"ok": False}
            acquired, w.acquired = w.acquired, {}
            w.state = "idle"
        self._release(acquired)
        self._kick_dispatch()
        return {"ok": True}

    # ------------------------------------------------------------------
    # per-node observability (reference: the dashboard reporter agent —
    # psutil stats + py-spy stack dumps/profiles proxied per worker)
    # ------------------------------------------------------------------

    def _worker_push_targets(self, worker_id: str | None = None):
        with self._workers_lock:
            return [(w.worker_id, w.push_addr)
                    for w in self._workers.values()
                    if w.push_addr is not None and w.state != "dead"
                    and (worker_id is None or w.worker_id == worker_id)]

    def rpc_worker_stacks(self, conn, send_lock, *,
                          worker_id: str | None = None):
        """Stack dumps of (one or all) local workers, keyed by worker id
        (py-spy ``dump`` analog via each worker's push port). Workers are
        queried in PARALLEL with a short timeout so one wedged worker
        costs 5s, not 5s x workers — and never hides the healthy ones."""
        out = {}
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(addr, timeout=5)
                stacks = client.call("dump_stacks")
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                stacks = {"error": repr(e)}
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                out[wid] = stacks

        threads = [threading.Thread(target=query, args=t, daemon=True)
                   for t in self._worker_push_targets(worker_id)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=8)
        return out

    def rpc_profile_worker(self, conn, send_lock, *, worker_id: str,
                           duration_s: float = 2.0, hz: int = 100):
        """Sampling CPU profile of one worker (py-spy ``record`` analog;
        collapsed-stack output for flamegraph tooling)."""
        targets = self._worker_push_targets(worker_id)
        if not targets:
            # sentinel (not a failure): lets cluster-wide callers keep
            # searching other nodes without conflating "lives elsewhere"
            # with a genuine profile error
            return {"not_found": True,
                    "error": f"no live worker {worker_id!r} here"}
        _, addr = targets[0]
        client = None
        try:
            client = RpcClient(addr, timeout=duration_s + 30)
            return client.call("profile", duration_s=duration_s, hz=hz)
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}
        finally:
            if client is not None:
                client.close()

    def rpc_node_info(self, conn, send_lock):
        return {"node_id": self.node_id, "store_name": self.store_name,
                "address": self.address, "resources": self.total_resources,
                "available": self._avail_snapshot(),
                "num_workers": len(self._workers),
                "spill_stats": dict(self.spill_stats)}

    # ------------------------------------------------------------------
    # background loops
    # ------------------------------------------------------------------

    def _heartbeat_loop(self):
        ticks = 0
        while not self._stopping:
            self._interruptible_sleep(self._hb_interval)
            if self._stopping:
                return
            ticks += 1
            if ticks % 2 == 0:
                try:
                    self._reconcile_locations()
                except Exception:  # noqa: BLE001 - next tick retries
                    pass
            try:
                stats = {}
                if ticks % 4 == 0:   # host sampling is cheap but not free
                    from ray_tpu.util.profiling import host_stats

                    stats = host_stats(self._spill_dir)
                with self._gcs_lock:
                    reply = self._gcs.call("heartbeat", node_id=self.node_id,
                                           available=self._avail_snapshot(),
                                           host_stats=stats or None)
                if reply.get("reregister"):
                    with self._gcs_lock:
                        self._gcs.call(
                            "register_node", node_id=self.node_id,
                            address=self.address, store_name=self.store_name,
                            resources=self.total_resources,
                            labels=self.labels)
            except Exception:  # noqa: BLE001 - gcs down; keep trying
                pass

    # ------------------------------------------------------------------
    # memory monitor (reference: MemoryMonitor common/memory_monitor.h:52
    # driving the raylet's WorkerKillingPolicy — kill the newest retriable
    # task's worker first so forward progress is preserved)
    # ------------------------------------------------------------------

    @staticmethod
    def _host_memory_fraction() -> float:
        """Used fraction of host memory from /proc/meminfo (the reference
        also honors cgroup limits; host-level covers TPU-VM deployments)."""
        total = avail = None
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        break
        except OSError:
            return 0.0
        if not total or avail is None:
            return 0.0
        return 1.0 - avail / total

    def _interruptible_sleep(self, seconds: float):
        """Sleep in small increments so background loops observe
        ``_stopping`` within ~0.1s — stop() joins them with a short
        timeout before munmapping the store, and a loop that oversleeps
        the join touches freed memory (segfault, not an exception)."""
        deadline = time.monotonic() + seconds
        while not self._stopping:
            remain = deadline - time.monotonic()
            if remain <= 0:
                return
            time.sleep(min(0.1, remain))

    def _memory_monitor_loop(self):
        while not self._stopping:
            self._interruptible_sleep(self._mem_refresh_s)
            if self._stopping:
                return
            if self._host_memory_fraction() < self._mem_threshold:
                continue
            if self._kill_one_for_memory():
                self._interruptible_sleep(1.0)  # let the kill take effect

    def _kill_one_for_memory(self) -> bool:
        """Pick and kill one worker to relieve pressure. Policy (reference
        worker_killing_policy_retriable_fifo.cc): newest-started RETRIABLE
        task first (its re-execution is cheapest and guaranteed safe),
        then newest non-retriable task worker; actors are never chosen —
        their state is not re-executable (the reference's group-by-owner
        policy similarly deprioritizes them)."""
        with self._workers_lock:
            # select AND kill inside the lock: a victim finishing its task
            # in between would take the SIGKILL for a brand-new task
            busy = [(w, w.current_task, w.dispatched_at)
                    for w in self._workers.values()
                    if w.state == "busy" and w.current_task is not None
                    and w.proc is not None]
            # leased workers are candidates too: their owner observes the
            # break, queries worker_death_info, and applies ITS OOM retry
            # budget (this raylet does not know the task)
            leased = [(w, None, w.dispatched_at)
                      for w in self._workers.values()
                      if w.state == "leased" and w.proc is not None]
            if not busy and not leased:
                return False
            busy.sort(key=lambda it: it[2])   # oldest-dispatched first
            leased.sort(key=lambda it: it[2])
            retriable = [it for it in busy
                         if it[1].get("max_retries", 0) > 0]
            # newest-dispatched first among: retriable (cheapest safe
            # re-run), then leased (owner-managed retry), then the rest
            victim = (retriable or leased or busy)[-1][0]
            victim.oom_killed = True
            try:
                victim.proc.kill()
            except OSError:
                victim.oom_killed = False  # a later crash is NOT an OOM
                return False
        return True

    def _monitor_loop(self):
        """Reap dead worker processes (reference: worker failure detection
        via socket + SIGCHLD in NodeManager)."""
        while not self._stopping:
            time.sleep(0.1)
            with self._workers_lock:
                dead = [w for w in self._workers.values()
                        if w.proc is not None and w.proc.poll() is not None
                        and w.state != "dead"]
            for w in dead:
                self._on_worker_gone(w)


def env_get_default(key: str, default: str) -> str:
    v = os.environ.get(key)
    return v if v else default


def _worker_pythonpath(current: str) -> str:
    """PYTHONPATH for spawned workers: the ray_tpu package root plus the
    inherited entries, minus directories that install a ``sitecustomize``
    hook — such hooks (e.g. a driver-side TPU tunnel plugin) eagerly import
    heavyweight runtimes and add seconds to EVERY worker spawn. Set
    RAY_TPU_WORKER_KEEP_SITE=1 to keep them (workers that must dial the
    TPU backend through the site hook)."""
    import ray_tpu
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))
    entries = [pkg_root]
    keep_site = os.environ.get("RAY_TPU_WORKER_KEEP_SITE") == "1"
    for p in current.split(os.pathsep):
        if not p or p == pkg_root:
            continue
        if not keep_site and os.path.exists(
                os.path.join(p, "sitecustomize.py")):
            continue
        entries.append(p)
    return os.pathsep.join(entries)


def main():  # runs a raylet as a standalone process (cluster_utils spawns it)
    import json
    import signal
    cfg = json.loads(sys.argv[1])
    raylet = Raylet(
        node_id=cfg["node_id"],
        gcs_address=tuple(cfg["gcs_address"]),
        resources=cfg["resources"],
        store_capacity=cfg.get("store_capacity", 1 << 30),
        labels=cfg.get("labels"),
        infeasible_timeout_s=cfg.get("infeasible_timeout_s", 10.0),
    )
    stop_ev = threading.Event()
    # graceful shutdown must run on SIGTERM too (Cluster.remove_node uses
    # terminate()); otherwise the shm segment leaks in /dev/shm
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    signal.signal(signal.SIGINT, lambda *_: stop_ev.set())
    raylet.start()
    # signal readiness to the parent via stdout
    print(json.dumps({"address": raylet.address,
                      "store_name": raylet.store_name}), flush=True)
    try:
        stop_ev.wait()
    finally:
        raylet.stop()


if __name__ == "__main__":
    main()
