"""Raylet: per-node manager — scheduler, worker pool, object manager.

Reference analog: ``src/ray/raylet/`` — ``NodeManager`` (node_manager.h:125)
on one event loop hosting the local scheduler (``ClusterTaskManager`` /
``LocalTaskManager``), the worker pool (``worker_pool.cc``), and the object
manager (``src/ray/object_manager/`` — pull/push of objects between nodes).
Like the reference, those are separate components owned by this node
manager — ``runtime/scheduler.py`` (queue/dispatch/leases/resources),
``runtime/worker_pool.py`` (spawn/registration/death/OOM policy),
``runtime/object_manager.py`` (pins/spill/transfer/pulls) — while the
raylet keeps placement routing, actors, cancellation, and the RPC surface.

Differences by design (TPU-host build, single-controller Python services):
- workers attach the node's C++ shm store directly (no UDS protocol hop);
- spillback consults the GCS resource view instead of gossiped snapshots
  (the ray_syncer analog is the heartbeat's available-resources report);
- node-to-node object transfer is a pull-only fetch RPC (the reference's
  PushManager handles proactive pushes; pull covers get()/dependency flow).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.runtime import object_codec
from ray_tpu.runtime.gcs import _fits
from ray_tpu.runtime.object_manager import LocalObjectManager
from ray_tpu.runtime.rpc import (
    ReconnectingRpcClient,
    RpcClient,
    RpcServer,
    send_msg,
)
from ray_tpu.runtime.scheduler import TaskScheduler
from ray_tpu.runtime.worker_pool import WorkerHandle, WorkerPool  # noqa: F401
# WorkerHandle is re-exported: it is part of this module's historical API.


class Raylet(RpcServer):
    def __init__(self, *, node_id: str, gcs_address, resources: dict,
                 store_capacity: int = 1 << 30, host: str = "127.0.0.1",
                 labels: dict | None = None,
                 heartbeat_interval_s: float | None = None,
                 infeasible_timeout_s: float = 10.0):
        super().__init__(host, 0)
        self.fault_label = "raylet"   # fault-injection endpoint label
        self.node_id = node_id
        self.gcs_address = tuple(gcs_address)
        from ray_tpu.runtime import fault_injection as _fi
        _fi.maybe_init_from_config(self.gcs_address)
        self.store_name = f"/raytpu_{os.getpid()}_{node_id[:8]}"
        self.store = ShmObjectStore(self.store_name, capacity=store_capacity,
                                    create=True)
        self.labels = labels or {}
        # per-worker stdout/stderr capture + forwarding to the driver
        # (reference: the log_monitor process tailing the session log
        # dir); workers write to files here, _log_monitor_loop tails
        import tempfile

        self.log_dir = tempfile.mkdtemp(
            prefix=f"raytpu-logs-{node_id[:8]}-")

        # reconnecting: survives a GCS restart (file-backed recovery)
        self._gcs = ReconnectingRpcClient(self.gcs_address,
                                          label="raylet")
        self._gcs_lock = threading.Lock()   # RpcClient is thread-safe; lock
                                            # keeps call+interpret atomic
        # LIVENESS gets its own connection + lock: on the shared channel
        # a task-flood's pick_node/spillback burst queues hundreds of
        # lock-waiters ahead of the beat, and the GCS falsely declares
        # this node dead mid-flood (seen at the 2k-actor envelope tier).
        self._gcs_beat = ReconnectingRpcClient(self.gcs_address,
                                               label="raylet")
        self._gcs_beat_lock = threading.Lock()
        self._peers: dict[str, RpcClient] = {}
        self._peer_addrs: dict[str, tuple] = {}
        self._peers_lock = threading.Lock()

        self.workers = WorkerPool(
            self, max_workers=max(1, int(resources.get("CPU", 1))))
        # (actor_id, incarnation) placements currently inside spawn() —
        # the host_actor idempotency window (see rpc_host_actor)
        # (actor_id, incarnation) -> in-flight hosting attempt: event +
        # outcome, so a deduped GCS retry can RETURN THE FIRST CALL'S
        # RESULT instead of unconditional success (an unconditional ok
        # for a first call that then failed — with its error reply lost
        # on the dead channel that caused the retry — left actors
        # PENDING forever with no failure report)
        self._pending_hosts: dict[tuple, dict] = {}
        # report_objects idempotency: token -> first reply (bounded)
        from collections import OrderedDict
        self._report_tokens: OrderedDict[str, dict] = OrderedDict()
        self._report_tokens_lock = threading.Lock()
        self.scheduler = TaskScheduler(
            self, resources=resources,
            infeasible_timeout_s=infeasible_timeout_s)
        self._threads: list[threading.Thread] = []
        from ray_tpu.utils.config import get_config
        _cfg = get_config()
        self._hb_interval = (heartbeat_interval_s
                             if heartbeat_interval_s is not None
                             else _cfg.raylet_heartbeat_interval_s)
        self._spillback_queue_depth = _cfg.scheduler_spillback_queue_depth
        # versioned resource sync (reference: ray_syncer.h:86): local
        # resource mutations push to the GCS at RPC latency; heartbeats
        # carry only the version. The view carries queue depth too so
        # placement can prefer shallow queues when everyone is busy.
        from ray_tpu.runtime.resource_sync import ResourceSyncer
        self.resource_syncer = ResourceSyncer(
            self, self._avail_snapshot,
            load_fn=lambda: len(self.scheduler.ready),
            push_delay_s=_cfg.resource_sync_push_delay_s)
        self.scheduler.on_resources_changed = \
            self.resource_syncer.mark_changed
        self.scheduler.on_queue_changed = \
            self.resource_syncer.mark_changed
        self._mem_threshold = _cfg.memory_usage_threshold
        self._mem_refresh_s = max(_cfg.memory_monitor_refresh_ms, 50) / 1e3
        # actor_ready acks coalesce here: worker ready messages buffer
        # and a flusher ships ONE actors_ready batch to the GCS per
        # linger window (was one GCS call per worker message — an actor
        # flood paid a full control-plane RTT per actor)
        self._ready_buf: list[dict] = []
        self._ready_cv = threading.Condition()
        self._ready_linger_s = _cfg.actor_ready_linger_s
        self.objects = LocalObjectManager(
            self, store=self.store, store_capacity=store_capacity, cfg=_cfg)
        # metrics plane: this raylet's registry pushes to the GCS under
        # its node id; grant latency is the raylet-side lease stage
        from ray_tpu.runtime.metrics_plane import MetricsPusher
        from ray_tpu.util import metrics as _metrics
        self._metrics_pusher = MetricsPusher(
            self.gcs_address, src=self.node_id[:12], kind="raylet")
        # memory plane: node occupancy decomposition rides the metric
        # frames as a live mem/node annex (in in-process clusters the
        # driver's pusher ships it — the annex registry is process-wide
        # and keys carry the node id)
        from ray_tpu.runtime import metrics_plane as _mp
        self._mem_annex_key = f"mem/node/{self.node_id[:12]}"

        def _mem_node_annex():
            if self._stopping:
                return None
            occ = self.objects.occupancy()
            occ["node_id"] = self.node_id
            occ["spilled_oids"] = self.objects.spilled_oids()
            occ["being_pulled_oids"] = sorted(self.objects.being_pulled())
            return occ

        _mp.set_annex_provider(self._mem_annex_key, _mem_node_annex)
        self._h_lease_grant = _metrics.histogram(
            "ray_tpu_lease_grant_s",
            "raylet-side lease grant latency (request to grant, parking "
            "included)").handle()
        # per-node live resource gauges (dashboard per-resource panels):
        # sampled on the heartbeat cadence, pushed with src=node_id so
        # /api/metrics/query?group_by=src yields one series per node
        self._g_cpu = _metrics.gauge(
            "ray_tpu_node_cpu_load",
            "1-min load average / cpu count, per node")
        self._g_mem = _metrics.gauge(
            "ray_tpu_node_mem_used_frac",
            "used host memory fraction, per node")

    # component-facing compatibility views (tests, the dashboard, and the
    # worker pool read these under their historical names)
    @property
    def _workers(self):
        return self.workers.workers

    @property
    def spill_stats(self):
        return self.objects.spill_stats

    @property
    def total_resources(self):
        return self.scheduler.total_resources

    @property
    def available(self):
        return self.scheduler.available

    @property
    def infeasible_timeout_s(self):
        return self.scheduler.infeasible_timeout_s

    def _kick_dispatch(self):
        self.scheduler.kick()

    def _release(self, demand: dict):
        self.scheduler.release(demand)

    def _enqueue(self, task: dict):
        self.scheduler.enqueue(task)

    def _avail_snapshot(self) -> dict:
        return self.scheduler.avail_snapshot()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self):
        super().start()
        with self._gcs_lock:
            self._gcs.call(
                "register_node", node_id=self.node_id, address=self.address,
                store_name=self.store_name, resources=self.total_resources,
                labels=self.labels)
        self.resource_syncer.start()
        loops = [self.scheduler.dispatch_loop, self._heartbeat_loop,
                 self.workers.monitor_loop, self.scheduler.infeasible_loop,
                 self.objects.location_flush_loop,
                 self._log_monitor_loop,
                 self.workers.prestart_policy_loop,
                 self._ready_flush_loop]
        if self.objects.spill_enabled:
            loops.append(self.objects.spill_loop)
        if self._mem_threshold > 0:
            loops.append(lambda: self.workers.memory_monitor_loop(
                self._mem_threshold, self._mem_refresh_s))
        for target in loops:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        self._metrics_pusher.start()
        self._spawn_dashboard_agent()
        return self

    def _spawn_dashboard_agent(self):
        """Per-node observability agent as its OWN process (reference:
        dashboard/agent.py) — host sampling and profiling queries must
        not share the raylet's threads. Exits on its own when this
        raylet's RPC server goes away."""
        import json as _json
        import subprocess

        from ray_tpu.utils.config import get_config

        self._agent_proc = None
        if not get_config().dashboard_agent_enabled:
            return
        cfg = {"node_id": self.node_id,
               "raylet_address": list(self.address),
               "gcs_address": list(self.gcs_address),
               "log_dir": self.log_dir,
               "spill_dir": (self.objects.spill_dir
                             if self.objects.spill_is_local else None)}
        # same PYTHONPATH stripping the worker spawn does: a
        # sitecustomize hook (TPU tunnel plugin) imports jax at EVERY
        # interpreter start — ~2 s of CPU the agent burns mid-workload
        # on small hosts, for a process that never touches a device
        from ray_tpu.runtime.worker_pool import _worker_pythonpath

        env = dict(os.environ)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        env["JAX_PLATFORMS"] = "cpu"
        try:
            self._agent_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.dashboard_agent",
                 _json.dumps(cfg)], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except Exception:  # noqa: BLE001 - observability only
            self._agent_proc = None

    def _log_monitor_loop(self, poll_s: float = 0.25,
                          dead_linger_s: float = 5.0):
        """Tail every capture file in the log dir and ship new COMPLETE
        lines to the GCS LogStore over ``push_logs`` (reference:
        log_monitor.py). Two file kinds coexist: ``<proc>.log`` is the
        in-process tee's stamped+rotated output (parsed per line, epoch
        headers tracked so offsets stay attributable across rotation);
        ``<proc>.out/.err`` is the raw Popen fd capture that only
        interpreter-level crashes write to (shipped unparsed). Scanning
        the DIRECTORY (not live worker handles) means a crashed worker's
        final output — its traceback — still ships even though the pool
        reaps the handle within ~0.1s; fully-drained files of dead
        workers are deleted after a short linger so dicts and disk stay
        bounded under worker churn.

        Drop-not-block: pushes go over a dedicated short-timeout client
        (fault label "metrics" — a metrics↔GCS partition covers logs
        too) into a bounded pending deque; a slow or partitioned GCS
        costs at most one 2s timeout per tick and then old batches,
        never task execution."""
        from collections import deque as _deque

        from ray_tpu.runtime import log_plane as _log_plane
        from ray_tpu.utils.config import get_config

        offsets: dict[str, int] = {}        # path -> bytes consumed
        partial: dict[str, bytes] = {}      # path -> incomplete tail
        epochs: dict[str, int] = {}         # path -> live generation
        inodes: dict[str, int] = {}
        pid_of: dict[str, int] = {}         # filename stem -> pid
        dead_since: dict[str, float] = {}
        pending: _deque = _deque(maxlen=max(
            8, int(get_config().log_push_buffer)))
        self._log_push_client = None
        self._log_push_dropped = 0

        def _parse_block(path, name, data, base_off, out):
            """Split ``data`` (starting at byte ``base_off``) into wire
            line tuples, tracking epoch headers; incomplete tail bytes
            go back to ``partial``."""
            lines = data.split(b"\n")
            if lines and lines[-1]:
                partial[path] = lines[-1]
            else:
                partial.pop(path, None)
            lines = lines[:-1]
            stamped = name.endswith(".log")
            stream_default = "e" if name.endswith(".err") else "o"
            off = base_off
            cur = None               # (epoch, [wire tuples])
            for raw in lines:
                text = raw.decode("utf-8", "replace")
                start = off
                off += len(raw) + 1
                if stamped:
                    ep = _log_plane.parse_epoch(text)
                    if ep is not None:
                        epochs[path] = ep
                        continue
                    parsed = _log_plane.parse_line(text)
                    ts, stream, trace, task, tname, job, body = parsed
                    rec = (start, ts, stream, body, trace, task, tname,
                           job)
                else:
                    rec = (start, time.time(), stream_default, text,
                           None, None, None, None)
                epoch = epochs.get(path, 0) if stamped else 0
                if cur is None or cur[0] != epoch or len(cur[1]) >= 500:
                    cur = (epoch, [])
                    out.append((path, name, epoch, cur[1]))
                cur[1].append(rec)

        while not self._stopping:
            with self.workers.lock:
                live = {h.worker_id[:12]: (h.proc.pid if h.proc else 0)
                        for h in self.workers.workers.values()}
            # zygote templates log here too; without this their capture
            # files read as dead-worker leftovers and get deleted
            live.update(self.workers.prestart.log_stems())
            pid_of.update(live)
            blocks = []   # (path, name, epoch, [wire tuples])
            try:
                names = sorted(os.listdir(self.log_dir))
            except OSError:
                names = []
            for name in names:
                stem, _, ext = name.rpartition(".")
                if ext not in ("log", "out", "err"):
                    continue   # rotated generations read on demand below
                path = os.path.join(self.log_dir, name)
                short = stem[len("worker-"):] if stem.startswith(
                    "worker-") else stem
                try:
                    st = os.stat(path)
                    size, ino = st.st_size, st.st_ino
                except OSError:
                    continue
                off = offsets.get(path, 0)
                if ext == "log" and (ino != inodes.setdefault(path, ino)
                                     or size < off):
                    # the live file rotated out from under us: drain the
                    # unread remainder from the shifted generation, then
                    # restart at the new file's epoch header
                    prev = f"{path}.1"
                    try:
                        psize = os.path.getsize(prev)
                        if psize > off:
                            tail = partial.pop(path, b"")
                            with open(prev, "rb") as f:
                                f.seek(off)
                                data = tail + f.read(
                                    min(psize - off, 1 << 20))
                            _parse_block(path, name, data,
                                         off - len(tail), blocks)
                    except OSError:
                        pass
                    partial.pop(path, None)
                    offsets[path] = off = 0
                    inodes[path] = ino
                if size > off:
                    take = min(size - off, 1 << 20)
                    try:
                        with open(path, "rb") as f:
                            f.seek(off)
                            tail = partial.pop(path, b"")
                            data = tail + f.read(take)
                    except OSError:
                        continue
                    offsets[path] = off + take
                    _parse_block(path, name, data, off - len(tail),
                                 blocks)
                elif short not in live and not stem.startswith(
                        ("raylet", "gcs", "driver")):
                    # drained file of a dead worker: linger, then drop
                    first = dead_since.setdefault(path, time.monotonic())
                    if time.monotonic() - first > dead_linger_s:
                        tail = partial.get(path)
                        if tail:
                            # a crashed worker's final line may lack a
                            # trailing newline — ship it before cleanup
                            _parse_block(path, name, tail + b"\n",
                                         offsets.get(path, 0) -
                                         len(tail), blocks)
                        for d in (offsets, partial, dead_since, epochs,
                                  inodes):
                            d.pop(path, None)
                        pid_of.pop(short, None)
                        for gen in [path] + [f"{path}.{i}"
                                             for i in range(1, 10)]:
                            try:
                                os.unlink(gen)
                            except OSError:
                                if gen != path:
                                    break   # no further generations
            for path, name, epoch, recs in blocks:
                if not recs:
                    continue
                stem = name.rpartition(".")[0]
                short = stem[len("worker-"):] if stem.startswith(
                    "worker-") else stem
                before = len(pending)
                pending.append({
                    "proc": stem,
                    "pid": pid_of.get(short, 0),
                    "file": f"{name}@{epoch}",
                    "lines": recs,
                })
                if len(pending) == before:   # maxlen hit: oldest fell
                    self._log_push_dropped += 1
            if pending:
                try:
                    if self._log_push_client is None:
                        # dedicated short-timeout channel: the shared GCS
                        # client would serialize log pushes behind
                        # scheduling traffic (and vice versa on a stall)
                        self._log_push_client = RpcClient(
                            self.gcs_address, timeout=2.0,
                            label="metrics")
                    batch = list(pending)
                    self._log_push_client.call(
                        "push_logs", node_id=self.node_id, entries=batch)
                    for _ in batch:
                        if pending:
                            pending.popleft()
                except Exception:  # noqa: BLE001 - GCS slow/partitioned
                    try:
                        if self._log_push_client is not None:
                            self._log_push_client.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._log_push_client = None
            self._interruptible_sleep(poll_s)

    def stop(self):
        super().stop()
        try:
            from ray_tpu.runtime import metrics_plane as _mp
            _mp.set_annex_provider(self._mem_annex_key, None)
        except Exception:  # noqa: BLE001 - best-effort plane teardown
            pass
        self._metrics_pusher.stop()
        self.objects.stop()
        self.scheduler.stop()
        with self._ready_cv:
            self._ready_cv.notify_all()   # ready flusher exits
        # join background loops BEFORE closing the store: a mid-tick spill
        # loop dereferencing the munmapped segment is a segfault, not an
        # exception
        for t in self._threads:
            t.join(timeout=2.0)
        self.workers.stop()
        client = getattr(self, "_log_push_client", None)
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
        agent = getattr(self, "_agent_proc", None)
        if agent is not None and agent.poll() is None:
            agent.terminate()
        import shutil

        shutil.rmtree(self.log_dir, ignore_errors=True)
        try:
            self._gcs_beat.close()
        except OSError:
            pass
        self.store.close()
        self.objects.cleanup_disk()

    def _interruptible_sleep(self, seconds: float):
        """Sleep in small increments so background loops observe
        ``_stopping`` within ~0.1s — stop() joins them with a short
        timeout before munmapping the store, and a loop that oversleeps
        the join touches freed memory (segfault, not an exception)."""
        deadline = time.monotonic() + seconds
        while not self._stopping:
            remain = deadline - time.monotonic()
            if remain <= 0:
                return
            time.sleep(min(0.1, remain))

    # ------------------------------------------------------------------
    # worker pool RPC surface (logic: runtime/worker_pool.py)
    # ------------------------------------------------------------------

    def rpc_register_worker(self, conn, send_lock, *, worker_id,
                            push_addr=None):
        return self.workers.register(conn, send_lock, worker_id=worker_id,
                                     push_addr=push_addr)

    def rpc_runtime_env_failed(self, conn, send_lock, *, key: str,
                               error: str):
        """A worker died setting up its runtime env (e.g. pip install
        failure): fail every queued task with that env NOW and stop
        respawning workers for it for a while — otherwise the queue
        drives an infinite spawn/install/crash loop with the real error
        trapped in worker stderr."""
        from ray_tpu.utils import exceptions as exc

        self.workers.mark_bad_env(key, error)
        doomed = self.scheduler.drop_queued_with_env(key)
        for task in doomed:
            self._store_task_error(task, exc.RuntimeEnvSetupError(
                f"runtime env setup failed: {error}"))
        return {"failed_tasks": len(doomed)}

    def rpc_worker_death_info(self, conn, send_lock, *, worker_id: str,
                              timeout_s: float = 2.0):
        """Why a worker died (lease owners map a broken lease to e.g.
        OutOfMemoryError instead of a generic crash). The owner's lease
        connection breaks the instant the process dies — often BEFORE
        this raylet's channel reader records the death — so this briefly
        waits for the record instead of returning an empty answer."""
        deadline = time.monotonic() + timeout_s
        while True:
            info = self.workers.death_info(worker_id)
            if info is not None:
                return info
            if time.monotonic() >= deadline or self._stopping:
                return {}
            time.sleep(0.05)

    def _retry_or_fail_dead_worker_task(self, w: WorkerHandle, task: dict):
        """Retry/error policy for the in-flight task of a dead worker
        (called by WorkerPool.on_worker_gone)."""
        decided = all(self.store.contains(bytes.fromhex(o))
                      for o in task.get("return_oids", ()))
        if decided or task.get("cancelled"):
            pass   # cancelled (error pre-stored) or results written:
                   # a retry would re-run completed/cancelled work
        elif w.oom_killed:
            # OOM kills have their OWN budget (config task_oom_retries,
            # reference RAY_task_oom_retries): host pressure from an
            # unrelated process must not burn the task's max_retries
            # lineage budget, and re-dispatch backs off so a
            # still-pressured node doesn't churn through the budget in
            # a few monitor ticks.
            from ray_tpu.utils.config import get_config

            total = get_config().task_oom_retries
            left = task.get("_oom_retries_left", total)
            if left > 0:
                task["_oom_retries_left"] = left - 1
                delay = min(8.0, 1.0 * 2 ** (total - left))
                self.scheduler.defer_enqueue(task, delay)
            else:
                from ray_tpu.utils import exceptions as exc
                self._store_task_error(task, exc.OutOfMemoryError(
                    f"task {task.get('name')}: worker killed to relieve "
                    f"host memory pressure (threshold "
                    f"{self._mem_threshold}; {total} OOM retries "
                    f"exhausted)"))
        elif task.get("max_retries", 0) > 0:
            task["max_retries"] -= 1
            self._enqueue(task)
        else:
            from ray_tpu.utils import exceptions as exc
            info = self.workers.death_info(w.worker_id) or {}
            reason = f"worker died executing {task.get('name')}"
            if info.get("crash_point"):
                reason += f" at crash point {info['crash_point']}"
            if info.get("last_words"):
                reason += ("; last words: "
                           + " | ".join(info["last_words"][-2:]))
            self._store_task_error(task, exc.WorkerCrashedError(reason))

    def _store_task_error(self, task: dict, error: BaseException):
        from ray_tpu.utils import exceptions as exc
        err = (error if isinstance(error, exc.RayTpuError)
               else exc.WorkerCrashedError(str(error)))
        om = self.objects
        for oid_hex in task.get("return_oids", ()):
            oid = bytes.fromhex(oid_hex)
            if not self.store.contains(oid):
                try:
                    # hold through seal→pin: the error object must not be
                    # evictable before the pin (same protocol as worker
                    # returns)
                    size = object_codec.put_value_durable(
                        self.store, oid, err, is_error=True, hold=True,
                        timeout_s=5.0,
                        request_space=(om.spill_bytes
                                       if om.spill_enabled else None))
                except Exception:  # noqa: BLE001 - already created etc.
                    continue
                om.pin_object(oid_hex)
                om.track_local(oid_hex)
                if size > 0:
                    self.store.release(oid)
                with self._gcs_lock:
                    self._gcs.call("add_object_location", oid=oid_hex,
                                   node_id=self.node_id, size=size)

    # ------------------------------------------------------------------
    # placement routing (reference: ClusterTaskManager spillback policy;
    # queueing/dispatch live in runtime/scheduler.py)
    # ------------------------------------------------------------------

    def rpc_submit_task(self, conn, send_lock, *, task: dict,
                        spill_count: int = 0):
        demand = task.get("resources", {})
        strategy = task.get("strategy", {})
        if strategy.get("kind") == "NODE_AFFINITY":
            target = strategy.get("node_id")
            if target and target != self.node_id:
                if self._forward(task, target, spill_count):
                    return {"ok": True, "node_id": target}
        if strategy.get("pg_id") and spill_count == 0:
            # placement-group tasks run on the bundle's reserved node
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        pg_id=strategy["pg_id"])
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count + 1):
                    return {"ok": True, "node_id": target}
        if not _fits(demand, self.total_resources) or (
                strategy.get("kind") == "SPREAD" and spill_count == 0):
            # infeasible here (or spread): ask GCS for a placement
            with self._gcs_lock:
                target = self._gcs.call(
                    "pick_node", demand=demand,
                    exclude=[] if _fits(demand, self.total_resources)
                    else [self.node_id],
                    pg_id=strategy.get("pg_id"))
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count):
                    return {"ok": True, "node_id": target}
            if not _fits(demand, self.total_resources):
                if (strategy.get("pg_id")
                        or strategy.get("kind") == "NODE_AFFINITY"):
                    # strategy-constrained tasks cannot be re-placed by
                    # the plain-demand retry loop (it would escape the PG
                    # reservation / ping-pong on affinity) — keep the
                    # immediate infeasible error for them
                    self._store_task_error(task, ValueError(
                        f"task {task.get('name')} demands {demand}: "
                        f"infeasible for its placement constraint"))
                    return {"ok": False, "reason": "infeasible"}
                # Cluster-wide infeasible: PARK the task and advertise the
                # unmet demand so the autoscaler can provision for it
                # (reference: infeasible queue feeding
                # GcsAutoscalerStateManager). Errors only after the grace
                # window — a fixed cluster still fails fast enough.
                self.scheduler.park_infeasible(task, demand)
                return {"ok": True, "parked": "infeasible"}
        elif spill_count < 2 and (
                not _fits(demand, self._avail_snapshot())
                or len(self.scheduler.ready)
                > self._spillback_queue_depth):
            # busy OR deeply queued here: one spillback attempt through
            # the GCS view. The QUEUE-DEPTH clause matters at flood
            # scale: per-task acquire/release keeps `available` looking
            # healthy on average, so without it a 200k-task burst piles
            # onto one node's queue while the rest of the cluster idles
            # (reference: hybrid policy scores utilization, and deep
            # local queues spill — cluster_task_manager.cc).
            with self._gcs_lock:
                target = self._gcs.call("pick_node", demand=demand,
                                        exclude=[self.node_id],
                                        pg_id=strategy.get("pg_id"))
            if target is not None and target != self.node_id:
                if self._forward(task, target, spill_count + 1):
                    return {"ok": True, "node_id": target}
        self._enqueue(task)
        return {"ok": True, "node_id": self.node_id}

    def _forward(self, task: dict, node_id: str, spill_count: int) -> bool:
        peer = self._peer(node_id)
        if peer is None:
            return False
        try:
            peer.call("submit_task", task=task, spill_count=spill_count + 1)
            return True
        except Exception:  # noqa: BLE001 - peer died; fall back local
            return False

    def _peer(self, node_id: str) -> RpcClient | None:
        with self._peers_lock:
            client = self._peers.get(node_id)
            if client is not None and client._closed:
                # connection died (peer restarted/stopped): re-resolve
                self._peers.pop(node_id, None)
                self._peer_addrs.pop(node_id, None)
                client = None
        if client is not None:
            return client
        with self._gcs_lock:
            nodes = self._gcs.call("get_nodes", alive_only=True)
        for n in nodes:
            if n["node_id"] == node_id:
                try:
                    client = RpcClient(n["address"], label="raylet")
                except OSError:
                    return None
                with self._peers_lock:
                    self._peers[node_id] = client
                    self._peer_addrs[node_id] = tuple(n["address"])
                return client
        return None

    def _peer_address(self, node_id) -> tuple | None:
        if node_id is None or node_id == self.node_id:
            return None
        if self._peer(node_id) is None:
            return None
        with self._peers_lock:
            return self._peer_addrs.get(node_id)

    # ------------------------------------------------------------------
    # actors (GCS calls host_actor; raylet dedicates a worker)
    # ------------------------------------------------------------------

    def rpc_host_actor(self, conn, send_lock, *, actor_id, spec,
                       incarnation=0):
        """Dedicate a fresh worker to the actor and hand it the creation
        task (reference: GcsActorScheduler::LeaseWorkerFromNode + the
        worker-lease machinery in node_manager.cc:1778).

        IDEMPOTENT per (actor_id, incarnation): the GCS retries a
        placement once when the shared placement channel dies mid-call
        (it cannot know whether the first call landed), so a duplicate
        for an actor already spawning/live here must be a no-op success
        — hosting twice would run two copies of the actor. A duplicate
        arriving while the first call is STILL INSIDE spawn() waits for
        and returns the first call's actual outcome — its synchronous
        failure (try_acquire rejection) must not be masked by an
        unconditional ok when the first reply died with its channel."""
        key = (actor_id, incarnation)
        with self.workers.lock:
            entry = self._pending_hosts.get(key)
            if entry is None:
                for w in self.workers.workers.values():
                    if (w.state == "actor" and w.actor_id == actor_id
                            and w.incarnation == incarnation):
                        return {"ok": True, "dedup": True}
                entry = {"ev": threading.Event(), "result": None,
                         "error": None}
                self._pending_hosts[key] = entry
                owner = True
            else:
                owner = False
        if not owner:
            entry["ev"].wait(timeout=60.0)
            if entry["error"] is not None:
                raise entry["error"]
            if entry["result"] is not None:
                return {**entry["result"], "dedup": True}
            # first call still inside spawn after 60s: treat as in
            # progress (a dead spawn is caught by its own deliver path)
            return {"ok": True, "dedup": True}
        try:
            result = self._host_actor(actor_id, spec, incarnation)
            entry["result"] = result
            return result
        except BaseException as e:
            entry["error"] = e
            raise
        finally:
            entry["ev"].set()
            with self.workers.lock:
                self._pending_hosts.pop(key, None)

    def _host_actor(self, actor_id, spec, incarnation):
        demand = spec.get("resources", {})
        if not self.scheduler.try_acquire(demand):
            raise RuntimeError(
                f"node {self.node_id} cannot host actor: {demand} unavailable")
        # prestart fast path: dedicate a warm already-registered idle
        # worker (its conn is live, so _deliver sends create_actor
        # immediately — no interpreter boot on the actor-creation path);
        # otherwise spawn, which itself prefers a zygote fork
        handle = self.workers.take_idle_for_actor(spec.get("runtime_env"))
        if handle is None:
            handle = self.workers.spawn(spec.get("runtime_env"))
            handle.state = "actor"
        handle.actor_id = actor_id
        handle.incarnation = incarnation
        handle.acquired = dict(demand)

        def _deliver():
            # pip envs legitimately take minutes on a cold cache: give
            # the worker's registration the install window. The plain
            # window is generous too (flag): under an actor-flood spawn
            # storm a freshly forked interpreter can take >30s just to
            # get scheduled, and a worker that actually DIED is caught
            # by poll() below, not by this deadline.
            from ray_tpu.utils.config import get_config
            renv = (spec.get("runtime_env") or {})
            window = get_config().worker_register_timeout_s
            if renv.get("pip"):
                # an install never SHRINKS the window a plain env gets
                window = max(900.0, window)
            deadline = time.monotonic() + window
            while time.monotonic() < deadline and not self._stopping:
                if handle.conn is not None:
                    try:
                        send_msg(handle.conn,
                                 {"type": "create_actor", "actor_id": actor_id,
                                  "task": spec,
                                  "incarnation": incarnation},
                                 handle.send_lock)
                    except OSError:
                        self.workers.on_worker_gone(handle)
                    return
                if handle.proc is not None and handle.proc.poll() is not None:
                    reason = ("actor worker died during startup "
                              f"(exit code {handle.proc.returncode})")
                    break
                time.sleep(0.01)
            else:
                reason = ("actor worker failed to register within the "
                          "deadline")
            with self._gcs_lock:
                self._gcs.call("actor_failed", actor_id=actor_id,
                               reason=reason)
        threading.Thread(target=_deliver, daemon=True).start()
        return {"ok": True}

    def rpc_host_actors(self, conn, send_lock, *, actors: list):
        """Batched placement frame from the GCS executor: host each
        actor through the idempotent single-actor path, replying
        per-actor outcomes so one infeasible entry cannot fail its
        batch-mates (the GCS feeds failures to the restart/death path
        individually)."""
        results = []
        for ent in actors:
            try:
                res = self.rpc_host_actor(
                    None, None, actor_id=ent["actor_id"],
                    spec=ent["spec"],
                    incarnation=ent.get("incarnation", 0))
                results.append(res)
            except Exception as e:  # noqa: BLE001 - per-actor outcome
                results.append({"ok": False, "error": repr(e)})
        return {"results": results}

    def queue_actor_ready(self, actor_id: str, push_addr):
        """Buffer one worker's actor_ready for the batched GCS ack."""
        with self._ready_cv:
            self._ready_buf.append({"actor_id": actor_id,
                                    "push_addr": push_addr})
            self._ready_cv.notify_all()

    def _ready_flush_loop(self):
        while not self._stopping:
            with self._ready_cv:
                while not self._ready_buf and not self._stopping:
                    self._ready_cv.wait(0.5)
                if self._stopping:
                    return
            if self._ready_linger_s > 0:
                time.sleep(self._ready_linger_s)   # coalesce the burst
            with self._ready_cv:
                batch, self._ready_buf = self._ready_buf, []
            if not batch:
                continue
            try:
                with self._gcs_lock:
                    self._gcs.call("actors_ready", node_id=self.node_id,
                                   actors=batch)
            except Exception:  # noqa: BLE001 - requeue; reconnecting
                # client already burned its redial window, so an ack
                # lost here would strand the actors PENDING forever
                with self._ready_cv:
                    self._ready_buf = batch + self._ready_buf
                self._interruptible_sleep(0.2)

    def rpc_submit_actor_task(self, conn, send_lock, *, task: dict):
        actor_id = task["actor_id"]
        with self.workers.lock:
            target = None
            for w in self.workers.workers.values():
                if w.actor_id == actor_id and w.state == "actor":
                    target = w
                    break
        if target is None or target.conn is None:
            raise LookupError(f"actor {actor_id} not hosted here")
        if task.get("incarnation", 0) != target.incarnation:
            # caller's seq numbering belongs to a previous incarnation —
            # reject so it refreshes (reference: client resend protocol)
            raise LookupError(
                f"actor {actor_id} incarnation mismatch "
                f"(task {task.get('incarnation')} != {target.incarnation})")
        send_msg(target.conn, {"type": "actor_task", "task": task},
                 target.send_lock)
        return {"ok": True}

    def rpc_submit_actor_tasks(self, conn, send_lock, *, tasks: list):
        """Batched actor submission for actors served via this raylet
        (no direct push port): validates and forwards each task over the
        worker channel; one reply per frame."""
        for task in tasks:
            self.rpc_submit_actor_task(conn, send_lock, task=task)
        return {"ok": True}

    def rpc_kill_actor_worker(self, conn, send_lock, *, actor_id):
        with self.workers.lock:
            target = None
            for w in self.workers.workers.values():
                if w.actor_id == actor_id:
                    target = w
                    break
        if target is not None and target.proc is not None:
            target.proc.terminate()
        return {"ok": True}

    # ------------------------------------------------------------------
    # cancellation + explicit free
    # ------------------------------------------------------------------

    def rpc_free_objects(self, conn, send_lock, *, oids: list,
                         broadcast: bool = True):
        """Explicitly release object copies on this node (reference:
        ``ray.internal.free``): unpin, drop from shm and the spill dir,
        deregister the location. Owners drop lineage separately so a
        subsequent ``get`` raises ObjectLostError instead of
        resurrecting the object."""
        freed = self.objects.free_objects(oids)
        if broadcast:
            with self._gcs_lock:
                nodes = self._gcs.call("get_nodes", alive_only=True)
            for n in nodes:
                if n["node_id"] == self.node_id:
                    continue
                peer = self._peer(n["node_id"])
                if peer is None:
                    continue
                try:
                    peer.call("free_objects", oids=list(oids),
                              broadcast=False)
                except Exception:  # noqa: BLE001 - peer gone
                    continue
        return {"freed": freed}

    def rpc_cancel_task(self, conn, send_lock, *, oids: list,
                        force: bool = False, broadcast: bool = True):
        """Cancel the task owning these return oids (reference:
        ``CoreWorker::CancelTask`` → raylet CancelTask RPC): queued tasks
        are dequeued; a running task's worker gets SIGINT (``force``:
        SIGKILL). The TaskCancelledError return object is written FIRST —
        first-write-wins makes a racing normal completion a no-op.
        Already-finished tasks (return objects exist) are untouched."""
        from ray_tpu.utils import exceptions as exc

        targets = set(oids)
        if all(self.store.contains(bytes.fromhex(o)) for o in targets):
            return {"found": True, "state": "finished"}

        def matches(task):
            return task and targets & set(task.get("return_oids", ()))

        # queued here? Dequeued under the scheduler cv; the error store (a
        # durable put + GCS RPC) runs OUTSIDE the cv so dispatch/enqueue
        # never stall behind it. The cancelled flag also covers a task
        # already popped by the dispatch loop but not yet assigned.
        queued = self.scheduler.take_queued_matching(matches)
        if queued is not None:
            queued["cancelled"] = True
            self._store_task_error(queued, exc.TaskCancelledError(
                f"task {queued.get('name')} cancelled while queued"))
            return {"found": True, "state": "queued"}
        # running here?
        with self.workers.lock:
            victim = None
            task = None
            for w in self.workers.workers.values():
                if w.state == "busy" and matches(w.current_task):
                    victim = w
                    task = w.current_task   # captured under the lock
                    task["cancelled"] = True
                    break
        if victim is not None:
            # pre-store the cancelled error; the worker's own
            # (interrupted or successful) write loses the race. Known
            # best-effort window for MULTI-return tasks: if the worker is
            # concurrently writing its returns, the task can complete with
            # a mix of real values and TaskCancelledError across the
            # return set (each oid resolves first-write-wins
            # independently). Cancel is best-effort by contract — callers
            # must treat any TaskCancelledError among the returns as "the
            # task may have partially run".
            self._store_task_error(task, exc.TaskCancelledError(
                f"task {task.get('name')} cancelled while running"))
            with self.workers.lock:
                # re-verify AND signal under the lock: the worker may
                # have finished the target and been handed new work —
                # never deliver the kill/interrupt over someone else's
                # task (finish_task and dispatch both mutate
                # current_task under this lock)
                if victim.current_task is not task:
                    return {"found": True, "state": "running"}
                if force:
                    # no retry for a cancelled task: detach it first
                    victim.current_task = None
                    if victim.proc is not None:
                        try:
                            victim.proc.kill()
                        except OSError:
                            pass
                elif victim.proc is not None:
                    import signal

                    try:
                        victim.proc.send_signal(signal.SIGINT)
                    except OSError:
                        pass
            return {"found": True, "state": "running"}
        # parked infeasible here? (popped under the scheduler lock; the
        # durable error store runs outside it — park_infeasible on the
        # submit path contends for that lock)
        parked = self.scheduler.take_infeasible_matching(matches)
        if parked is not None:
            parked["cancelled"] = True
            self._store_task_error(parked, exc.TaskCancelledError(
                f"task {parked.get('name')} cancelled while infeasible"))
            return {"found": True, "state": "infeasible"}
        if broadcast:
            with self._gcs_lock:
                nodes = self._gcs.call("get_nodes", alive_only=True)
            for n in nodes:
                if n["node_id"] == self.node_id:
                    continue
                peer = self._peer(n["node_id"])
                if peer is None:
                    continue
                try:
                    reply = peer.call("cancel_task", oids=list(oids),
                                      force=force, broadcast=False)
                    if reply.get("found"):
                        return reply
                except Exception:  # noqa: BLE001 - peer gone
                    continue
        return {"found": False}

    # ------------------------------------------------------------------
    # object manager RPC surface (logic: runtime/object_manager.py)
    # ------------------------------------------------------------------

    def rpc_report_object(self, conn, send_lock, *, oid: str, size: int = 0):
        if not self.objects.report_object(oid, size):
            return {"ok": False, "reason": "object not present to pin"}
        return {"ok": True}

    def rpc_report_objects(self, conn, send_lock, *, entries: list,
                           token: str | None = None):
        """Batched report_object (workers buffer their task-return
        reports and flush together; each object is protected by its
        writer's seal-hold until the pin lands here).

        ``token`` makes the batch idempotent: the reporter holds one
        token across redials of the same batch, and a duplicate delivery
        (reply lost to a partition, or an injected duplicate) replays the
        first reply instead of re-running the pins."""
        if token is not None:
            with self._report_tokens_lock:
                cached = self._report_tokens.get(token)
            if cached is not None:
                return cached
        ok = []
        for oid, size in entries:
            if self.objects.report_object(oid, size):
                ok.append(oid)
        reply = {"ok": ok}
        if token is not None:
            with self._report_tokens_lock:
                self._report_tokens[token] = reply
                while len(self._report_tokens) > 4096:
                    self._report_tokens.popitem(last=False)
        return reply

    def rpc_request_space(self, conn, send_lock, *, nbytes: int = 0):
        return {"spilled": self.objects.request_space(nbytes)}

    def rpc_memory_stats(self, conn, send_lock):
        """Node-level memory-plane decomposition: store occupancy split
        by pinned-primary / cached-replica / spilled, cumulative
        spill/restore accounting, and recent make-room pressure events
        (util.state.memory_summary fans this out per node)."""
        occ = self.objects.occupancy()
        occ["node_id"] = self.node_id
        occ["being_pulled_oids"] = sorted(self.objects.being_pulled())
        return occ

    def rpc_fetch_object(self, conn, send_lock, *, oid: str):
        return self.objects.fetch_object(oid)

    def rpc_fetch_object_meta(self, conn, send_lock, *, oid: str):
        return self.objects.fetch_object_meta(oid)

    def rpc_fetch_object_chunk(self, conn, send_lock, *, oid: str,
                               offset: int, length: int):
        return self.objects.fetch_object_chunk(oid, offset, length)

    def rpc_ensure_local(self, conn, send_lock, *, oids: list,
                         timeout_s: float = 30.0):
        return self.objects.ensure_local(oids, timeout_s)

    # ------------------------------------------------------------------
    # cross-language object plane (reference: the C++/Java clients'
    # msgpack serialization — values cross here as plain data; the RPC
    # layer decodes/encodes the msgpack frames, runtime/xlang.py)
    # ------------------------------------------------------------------

    def rpc_xlang_put(self, conn, send_lock, *, value):
        """Store a plain-data value from an external-language client;
        returns the new object id (hex). The object is a normal store
        object (Python tasks read it natively)."""
        from ray_tpu.utils.ids import ObjectID

        oid = ObjectID.from_random()
        size = object_codec.put_value_durable(
            self.store, oid.binary(), value, hold=True,
            request_space=(self.objects.spill_bytes
                           if self.objects.spill_enabled else None))
        self.objects.pin_object(oid.hex())
        self.objects.track_local(oid.hex())
        if size > 0:
            self.store.release(oid.binary())
        self.objects.queue_location(oid.hex(), size)
        return {"oid": oid.hex()}

    def rpc_xlang_get(self, conn, send_lock, *, oid: str,
                      timeout_s: float = 30.0):
        """Resolve an object to a plain-data value for an external-
        language client: waits/pulls via ensure_local, decodes the stored
        object, and ships it back on the msgpack reply (values outside
        the cross-language domain fail the call, not the server)."""
        missing = self.objects.ensure_local([oid], timeout_s)
        if missing:
            raise TimeoutError(f"object {oid[:8]} not available within "
                               f"{timeout_s}s")
        value, is_error = object_codec.get_value(
            self.store, bytes.fromhex(oid), timeout_ms=0)
        if is_error:
            raise value
        return {"value": value}

    # ------------------------------------------------------------------
    # worker lease RPC surface (logic: runtime/scheduler.py)
    # ------------------------------------------------------------------

    def rpc_request_lease(self, conn, send_lock, *, demand: dict,
                          runtime_env: dict | None = None,
                          timeout_s: float = 10.0, spill_count: int = 0,
                          token: str | None = None):
        from ray_tpu.util import metrics as _metrics

        t0 = time.perf_counter()
        resp = self.scheduler.request_lease(demand, runtime_env, timeout_s,
                                            spill_count, token=token)
        if resp.get("ok") and _metrics.enabled():
            self._h_lease_grant.observe(time.perf_counter() - t0)
        return resp

    def rpc_cancel_leased(self, conn, send_lock, *, worker_id: str,
                          task: dict, force: bool = False):
        """Cancel a task running on a LEASED worker. The owner (who alone
        knows what its lease is executing) names the worker and supplies
        the task's return oids; this raylet pre-stores the cancel error
        and interrupts (SIGINT) or kills the worker process."""
        from ray_tpu.utils import exceptions as exc

        with self.workers.lock:
            w = self.workers.workers.get(worker_id)
            if w is None or w.state != "leased" or w.proc is None:
                return {"found": False}
        task["cancelled"] = True
        self._store_task_error(task, exc.TaskCancelledError(
            f"task {task.get('name')} cancelled while running"))
        with self.workers.lock:
            w = self.workers.workers.get(worker_id)
            if w is None or w.state != "leased" or w.proc is None:
                return {"found": False}
            try:
                if force:
                    w.proc.kill()
                elif w.conn is not None:
                    # targeted: the worker interrupts the task BY ID
                    # (a raw SIGINT could hit a batchmate in a grouped
                    # push)
                    send_msg(w.conn, {"type": "cancel_push",
                                      "task_id": task.get("task_id", "")},
                             w.send_lock)
            except OSError:
                pass
        return {"found": True}

    def rpc_lease_closed(self, conn, send_lock, *, worker_id: str):
        """The worker's owner-facing connection dropped (lease returned or
        owner died): the worker and its resources go back to the pool."""
        with self.workers.lock:
            w = self.workers.workers.get(worker_id)
            if w is None or w.state != "leased":
                return {"ok": False}
            acquired, w.acquired = w.acquired, {}
            w.idle_since = time.monotonic()
            w.state = "idle"
        self._release(acquired)
        self._kick_dispatch()
        return {"ok": True}

    # ------------------------------------------------------------------
    # per-node observability (reference: the dashboard reporter agent —
    # psutil stats + py-spy stack dumps/profiles proxied per worker)
    # ------------------------------------------------------------------

    def rpc_worker_targets(self, conn, send_lock, *,
                           worker_id: str | None = None):
        """Live workers' (id, push_addr) pairs — the dashboard agent's
        one raylet dependency (it dials workers directly for stacks/
        profiles; reference: the reporter agent gets the worker list
        from its raylet)."""
        return [[wid, list(addr)]
                for wid, addr in self.workers.push_targets(worker_id)]

    def rpc_worker_stacks(self, conn, send_lock, *,
                          worker_id: str | None = None):
        """Stack dumps of (one or all) local workers, keyed by worker id
        (py-spy ``dump`` analog via each worker's push port). Workers are
        queried in PARALLEL with a short timeout so one wedged worker
        costs 5s, not 5s x workers — and never hides the healthy ones."""
        out = {}
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(addr, timeout=5, label="raylet")
                stacks = client.call("dump_stacks")
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                stacks = {"error": repr(e)}
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                out[wid] = stacks

        threads = [threading.Thread(target=query, args=t, daemon=True)
                   for t in self.workers.push_targets(worker_id)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=8)
        return out

    def rpc_profile_worker(self, conn, send_lock, *, worker_id: str,
                           duration_s: float = 2.0, hz: int = 100):
        """Sampling CPU profile of one worker (py-spy ``record`` analog;
        collapsed-stack output for flamegraph tooling)."""
        targets = self.workers.push_targets(worker_id)
        if not targets:
            # sentinel (not a failure): lets cluster-wide callers keep
            # searching other nodes without conflating "lives elsewhere"
            # with a genuine profile error
            return {"not_found": True,
                    "error": f"no live worker {worker_id!r} here"}
        _, addr = targets[0]
        client = None
        try:
            client = RpcClient(addr, timeout=duration_s + 30,
                               label="raylet")
            return client.call("profile", duration_s=duration_s, hz=hz)
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}
        finally:
            if client is not None:
                client.close()

    def rpc_dump_stacks(self, conn, send_lock):
        """One-shot per-thread stack dump of the raylet process itself
        (the workers' dumps come via rpc_worker_stacks)."""
        from ray_tpu.util.profiling import dump_stacks
        return {"stacks": dump_stacks()}

    def rpc_profile_node(self, conn, send_lock, *, duration_s: float = 2.0,
                         hz: int = 100, include_workers: bool = True,
                         include_raylet: bool = True):
        """One sampling window over this whole node: the raylet samples
        ITSELF while every local worker profiles concurrently over its
        push port (util.state.profile_cluster fans this per node). The
        worker windows overlap the raylet's, so the node costs one
        ``duration_s``, not one per process."""
        from ray_tpu.util.profiling import Sampler
        from ray_tpu.utils.config import get_config

        duration_s = min(float(duration_s),
                         float(get_config().profile_max_duration_s))
        workers: dict = {}
        errors: dict = {}
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(addr, timeout=duration_s + 30,
                                   label="raylet")
                prof = client.call("profile", duration_s=duration_s,
                                   hz=hz)
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                with out_lock:
                    errors[wid] = repr(e)
                return
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                workers[wid] = prof

        threads = []
        if include_workers:
            threads = [threading.Thread(target=query, args=t, daemon=True)
                       for t in self.workers.push_targets(None)]
        for t in threads:
            t.start()
        own = None
        if include_raylet:
            sampler = Sampler(
                hz=hz, exclude_threads={threading.get_ident()}).start()
            time.sleep(duration_s)
            own = sampler.stop()
        for t in threads:
            t.join(timeout=duration_s + 35)
        return {"raylet": own, "workers": workers, "errors": errors}

    def rpc_node_info(self, conn, send_lock):
        return {"node_id": self.node_id, "store_name": self.store_name,
                "address": self.address, "resources": self.total_resources,
                "available": self._avail_snapshot(),
                "num_workers": len(self.workers.workers),
                "spill_stats": dict(self.objects.spill_stats),
                "occupancy": self.objects.occupancy(),
                "prestart": self.workers.prestart.snapshot()}

    def rpc_stuck_calls(self, conn, send_lock, *, threshold_s=None):
        """In-flight calls older than the threshold on this NODE: the
        raylet's own registry plus every local worker's, collected in
        parallel over the worker push ports (same shape as
        rpc_worker_stacks: one wedged worker costs 5s, not 5s x N)."""
        from ray_tpu.util import tracing as _tracing
        out = {"raylet": _tracing.local_stuck_calls(threshold_s)}
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(addr, timeout=5, label="raylet")
                calls = client.call("stuck_calls",
                                    threshold_s=threshold_s)["calls"]
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                calls = {"error": repr(e)}
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                out[wid] = calls

        threads = [threading.Thread(target=query, args=t, daemon=True)
                   for t in self.workers.push_targets(None)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=8)
        return out

    def rpc_flight_record(self, conn, send_lock, *,
                          worker_id: str | None = None, last_s=None):
        """Flight-recorder snapshots for this node: the raylet's own
        ring plus (one or all) local workers'. Local memory only — a
        partitioned GCS cannot make this fail."""
        from ray_tpu.util import tracing as _tracing
        out = {}
        if worker_id is None:
            out["raylet"] = _tracing.flight_snapshot(last_s)
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(addr, timeout=5, label="raylet")
                snap = client.call("flight_record", last_s=last_s)
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                snap = {"error": repr(e)}
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                out[wid] = snap

        threads = [threading.Thread(target=query, args=t, daemon=True)
                   for t in self.workers.push_targets(worker_id)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=8)
        return out

    # ------------------------------------------------------------------
    # heartbeat
    # ------------------------------------------------------------------

    def _sample_node_gauges(self, stats: dict):
        """Feed the per-node dashboard panels. Prefers the host_stats
        sample (psutil); falls back to load average + /proc/meminfo so
        the panels work without psutil (Linux) and degrade to silence
        elsewhere."""
        try:
            if stats and "cpu_percent" in stats:
                self._g_cpu.set(stats["cpu_percent"] / 100.0)
            else:
                self._g_cpu.set(
                    os.getloadavg()[0] / max(1, os.cpu_count() or 1))
        except OSError:
            pass
        try:
            if stats and stats.get("mem_total"):
                self._g_mem.set(
                    1.0 - stats["mem_available"] / stats["mem_total"])
            else:
                meminfo = {}
                with open("/proc/meminfo") as f:
                    for line in f:
                        k, _, rest = line.partition(":")
                        meminfo[k] = float(rest.split()[0])
                total = meminfo.get("MemTotal", 0.0)
                avail = meminfo.get("MemAvailable", 0.0)
                if total > 0:
                    self._g_mem.set(1.0 - avail / total)
        except (OSError, IndexError, ValueError):
            pass

    def _heartbeat_loop(self):
        ticks = 0
        freed_acks: set[str] = set()
        while not self._stopping:
            self._interruptible_sleep(self._hb_interval)
            if self._stopping:
                return
            ticks += 1
            if ticks % 2 == 0:
                try:
                    self.objects.reconcile_locations()
                except Exception:  # noqa: BLE001 - next tick retries
                    pass
            try:
                stats = {}
                if ticks % 4 == 0:   # host sampling is cheap but not free
                    from ray_tpu.util.profiling import host_stats

                    stats = host_stats(
                        self.objects.spill_dir
                        if self.objects.spill_is_local else None)
                    self._sample_node_gauges(stats)
                acks = sorted(freed_acks) if freed_acks else None
                with self._gcs_beat_lock:
                    # liveness only, on the DEDICATED beat channel: the
                    # versioned syncer carries the resource view at RPC
                    # latency; the beat's payload is O(1) (the version)
                    # unless the GCS asks for a resync
                    reply = self._gcs_beat.call(
                        "heartbeat", node_id=self.node_id,
                        resource_version=self.resource_syncer
                        .pushed_version,
                        host_stats=stats or None,
                        freed_acks=acks)
                if acks:
                    freed_acks.difference_update(acks)
                if reply.get("reregister"):
                    with self._gcs_beat_lock:
                        self._gcs_beat.call(
                            "register_node", node_id=self.node_id,
                            address=self.address, store_name=self.store_name,
                            resources=self.total_resources,
                            labels=self.labels)
                    self.resource_syncer.force_push()
                elif reply.get("need_resources"):
                    # version mismatch (lost push / GCS restart): resync
                    self.resource_syncer.force_push()
                # refcount releases ride the heartbeat reply (at-least-
                # once: acked on the NEXT beat; freeing is idempotent)
                release = reply.get("release_oids")
                if release:
                    try:
                        self.objects.free_objects(release,
                                                  deregister=False)
                    finally:
                        freed_acks.update(release)
            except Exception:  # noqa: BLE001 - gcs down; keep trying
                pass


def main():  # runs a raylet as a standalone process (cluster_utils spawns it)
    import json
    import signal

    from ray_tpu.runtime import fault_injection as _fi
    # role stamp BEFORE construction: crash rules scoped proc="raylet"
    # may only ever kill external raylet processes like this one
    _fi.set_process_label("raylet")
    cfg = json.loads(sys.argv[1])
    raylet = Raylet(
        node_id=cfg["node_id"],
        gcs_address=tuple(cfg["gcs_address"]),
        resources=cfg["resources"],
        store_capacity=cfg.get("store_capacity", 1 << 30),
        labels=cfg.get("labels"),
        infeasible_timeout_s=cfg.get("infeasible_timeout_s", 10.0),
    )
    stop_ev = threading.Event()
    # graceful shutdown must run on SIGTERM too (Cluster.remove_node uses
    # terminate()); otherwise the shm segment leaks in /dev/shm
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    signal.signal(signal.SIGINT, lambda *_: stop_ev.set())
    # flight recorder: dump before a SIGTERM death (chains to the stop
    # handler above)
    from ray_tpu.util import tracing as _tracing
    _tracing.install_crash_dump()
    raylet.start()
    # signal readiness to the parent via stdout
    print(json.dumps({"address": raylet.address,
                      "store_name": raylet.store_name}), flush=True)
    # capture AFTER the readiness line: the parent blocks on reading the
    # JSON above from the real stdout pipe. The raylet's own log monitor
    # tails this file, so raylet prints reach the cluster log store like
    # any worker's.
    from ray_tpu.runtime import log_plane as _log_plane
    _log_plane.install_capture(f"raylet-{raylet.node_id[:12]}",
                               log_dir=raylet.log_dir)
    try:
        stop_ev.wait()
    finally:
        _log_plane.uninstall_capture()
        raylet.stop()


if __name__ == "__main__":
    main()
