"""Cluster metrics plane: push-aggregated time series.

Reference analog: the opencensus stats registry in every process pushed
to a per-node metrics agent and scraped by Prometheus
(``src/ray/stats/`` + ``dashboard/modules/reporter/``). Here each
process (driver, worker runtime, raylet, the GCS itself) periodically
snapshots its local ``ray_tpu.util.metrics`` registry as a DELTA frame
and pushes it to the GCS over ``rpc_push_metrics``; the GCS keeps a
ring buffer of aggregation windows per (metric, tags) and answers
range/instant queries over ``rpc_query_metrics`` (surfaced by
``ray_tpu.util.state.cluster_metrics``). Rolled windows fan out to
CH_METRICS subscribers through the same coalesced pushed-channel
machinery the actor location table uses.

Design invariant — STRICTLY BEST-EFFORT: nothing here may ever block or
slow a hot path. Instrumented call sites only touch the process-local
registry; all network IO happens on this module's dedicated pusher
thread, whose outbound buffer is bounded (oldest frames dropped on
overflow) and whose RPCs carry short timeouts. A dropped, delayed,
duplicated, or partitioned metrics frame costs observability fidelity,
never throughput (asserted in ``tests/test_chaos_partitions.py``).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ray_tpu.util import metrics as _metrics

# fault-injection endpoint label for pusher connections: chaos rules
# target the metrics plane by label ("metrics") or method
# ("push_metrics") without touching co-located control RPCs
FAULT_LABEL = "metrics"

# One pusher per PROCESS: the registry is process-local, so a second
# pusher in the same process (in-process GCS under a driver, in-worker
# runtime) would double-push every series under a second src tag.
_claim_lock = threading.Lock()
_claimed: str | None = None

# -- metric annexes ------------------------------------------------------
#
# Small opaque payloads piggybacked on metrics frames: a publisher
# (e.g. a serve replica's prefix-cache digest) registers them in the
# process-local annex registry; the process's pusher attaches the
# current annex set to its next push, and the GCS-side MetricsStore
# keeps the latest payload per (src, key) stamped with its push time.
# Same best-effort contract as the series: a lost annex costs routing
# fidelity, never correctness.
_annex_lock = threading.Lock()
_annexes: dict[str, tuple[float, object]] = {}
_annex_version = 0
# annex PROVIDERS: key -> zero-arg callable evaluated at snapshot time,
# for payloads that must reflect live state (the memory plane's
# ownership table) rather than a value frozen at publish time. A
# provider returning None skips the key this round; exceptions are
# swallowed (best-effort, same contract as the frames they ride).
_annex_providers: dict[str, object] = {}


def set_annex(key: str, payload) -> None:
    """Publish (payload) or retract (None) one annex under ``key``."""
    global _annex_version
    with _annex_lock:
        if payload is None:
            _annexes.pop(key, None)
        else:
            _annexes[key] = (time.time(), payload)
        _annex_version += 1


def set_annex_provider(key: str, fn) -> None:
    """Register (fn) or retract (None) a live annex under ``key``:
    ``fn()`` is called on every pusher snapshot and its return value
    ships as the payload. Providers re-ship on the pusher's periodic
    annex re-stamp cadence (``max(1.0, 2 * interval)``) even when no
    static annex changed, so a live table is never staler than ~2
    push intervals while the plane is healthy."""
    global _annex_version
    with _annex_lock:
        if fn is None:
            _annex_providers.pop(key, None)
        else:
            _annex_providers[key] = fn
        _annex_version += 1


def local_annexes() -> dict[str, tuple[float, object]]:
    """{key: (ts, payload)} snapshot of this process's annexes,
    providers included (evaluated now) — the memory plane's degraded
    local-mode answers read through this during GCS partitions."""
    with _annex_lock:
        out = dict(_annexes)
        providers = list(_annex_providers.items())
    now = time.time()
    for key, fn in providers:
        try:
            payload = fn()
        except Exception:  # noqa: BLE001 - provider is best-effort
            continue
        if payload is not None:
            out[key] = (now, payload)
    return out


def _annex_snapshot():
    with _annex_lock:
        ver = _annex_version
        out = {k: v[1] for k, v in _annexes.items()}
        providers = list(_annex_providers.items())
    # providers run OUTSIDE the annex lock: they take their own locks
    # (refcount table) and must not order against annex publication
    for key, fn in providers:
        try:
            payload = fn()
        except Exception:  # noqa: BLE001 - provider is best-effort
            continue
        if payload is not None:
            out[key] = payload
    return ver, out


def claim_pusher(owner: str) -> bool:
    global _claimed
    with _claim_lock:
        if _claimed is None or _claimed == owner:
            _claimed = owner
            return True
        return False


def release_pusher(owner: str):
    global _claimed
    with _claim_lock:
        if _claimed == owner:
            _claimed = None


class MetricsPusher:
    """Per-process push loop: registry delta frames -> GCS, fire-and-
    forget. One daemon thread; hot paths never see it."""

    def __init__(self, gcs_address, src: str, *, kind: str = "worker",
                 interval_s: float | None = None):
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        self._addr = tuple(gcs_address)
        self._src = src
        self._kind = kind
        self._interval = (interval_s if interval_s is not None
                          else cfg.metrics_push_interval_s)
        self._buf: deque = deque()
        self._buf_cap = max(1, cfg.metrics_push_buffer)
        self._prev: dict | None = None
        self._annex_ver = -1
        self._annex_sent_t = 0.0
        self._client = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.pushed = 0
        self.dropped = 0
        self.pushed_spans = 0

    def start(self) -> "MetricsPusher":
        if not _metrics.enabled() or not claim_pusher(self._src):
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-pusher")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        release_pusher(self._src)
        client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except OSError:
                pass

    # -- push machinery ------------------------------------------------

    def _ensure_client(self):
        if self._client is None:
            from ray_tpu.runtime.rpc import RpcClient

            # short dial/read timeout: a partitioned GCS costs this
            # thread at most one timeout per tick, and nothing else
            self._client = RpcClient(self._addr, timeout=2.0,
                                     label=FAULT_LABEL)
        return self._client

    def flush_now(self):
        """One synchronous frame+push round (tests / bench teardown —
        same path the loop takes)."""
        self._tick()

    def _tick(self):
        frame, self._prev = _metrics.snapshot_delta(self._prev)
        if frame:
            if len(self._buf) >= self._buf_cap:
                self._buf.popleft()      # bounded: oldest frame drops
                self.dropped += 1
            self._buf.append((time.time(), frame))
        # annexes ride the first push of the tick; when nothing else is
        # queued but the annex set changed (or needs a freshness
        # re-stamp so GCS-side max_age filters don't expire a live
        # publisher), an empty frame carries them
        annex_ver, annex = _annex_snapshot()
        now = time.time()
        want_annex = bool(annex) and (
            annex_ver != self._annex_ver
            or now - self._annex_sent_t >= max(1.0, 2 * self._interval))
        if want_annex and not self._buf:
            self._buf.append((now, {}))
        while self._buf and not self._stop.is_set():
            ts, fr = self._buf[0]
            try:
                self._ensure_client().call(
                    "push_metrics", src=self._src, kind=self._kind,
                    ts=ts, frame=fr, timeout=2.0,
                    annex=(annex if want_annex else None))
            except Exception:  # noqa: BLE001 - best-effort: retry next tick
                client, self._client = self._client, None
                if client is not None:
                    try:
                        client.close()
                    except OSError:
                        pass
                return
            self._buf.popleft()
            self.pushed += 1
            if want_annex:
                self._annex_ver = annex_ver
                self._annex_sent_t = now
                want_annex = False
        # trace spans ride the same tick AFTER the frame loop drained
        # cleanly (a failed frame push already spent this tick's one
        # timeout — don't spend a second on a dead GCS). Same contract:
        # drop-not-block, bounded requeue on failure.
        self._push_spans()

    def _push_spans(self):
        from ray_tpu.util import tracing as _tracing

        if self._stop.is_set() or not _tracing.is_enabled():
            return
        spans = _tracing.drain_spans()
        if not spans:
            return
        try:
            self._ensure_client().call("push_spans", src=self._src,
                                       spans=spans, timeout=2.0)
            self.pushed_spans += len(spans)
        except Exception:  # noqa: BLE001 - best-effort: retry next tick
            _tracing.requeue_spans(spans)
            client, self._client = self._client, None
            if client is not None:
                try:
                    client.close()
                except OSError:
                    pass

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the plane must never die loudly
                pass


def _with_src(key: tuple, src: str) -> tuple:
    """Extend a series tag tuple with the pushing process's node/client
    id (sorted — tag tuples are canonical sorted item tuples)."""
    if any(k == "src" for k, _ in key):
        return key
    return tuple(sorted((*key, ("src", src))))


class MetricsStore:
    """GCS-side ring-buffer time-series store: the last N aggregation
    windows per (metric, tags+src). Ingest is additive (delta frames);
    queries merge windows in range and group by requested tag keys."""

    def __init__(self, window_s: float = 5.0, windows: int = 60,
                 on_roll=None):
        self._lock = threading.Lock()
        self._window_s = window_s
        self._ring: deque = deque(maxlen=max(1, windows))
        self._cur: dict = {}
        self._cur_start = time.time()
        self._on_roll = on_roll
        self.frames = 0
        # latest annex payload per (src, key), stamped with ingest time
        self._annex: dict = {}

    # -- ingest --------------------------------------------------------

    def ingest(self, src: str, frame: dict, ts: float | None = None):
        now = time.time()
        rolled = None
        with self._lock:
            rolled = self._maybe_roll_locked(now)
            for name, ent in frame.items():
                slot = self._cur.get(name)
                if slot is None:
                    slot = self._cur[name] = {
                        "kind": ent["kind"],
                        "boundaries": ent.get("boundaries"),
                        "series": {}}
                series = slot["series"]
                kind = ent["kind"]
                for key, payload in ent["series"].items():
                    key = _with_src(tuple(key), src)
                    if kind == "gauge":
                        series[key] = float(payload)
                    elif kind == "counter":
                        series[key] = series.get(key, 0.0) + float(payload)
                    else:
                        series[key] = _metrics.merge_hist(
                            series.get(key), payload)
            self.frames += 1
        if rolled is not None and self._on_roll is not None:
            try:
                self._on_roll(rolled)
            except Exception:  # noqa: BLE001 - publish is best-effort
                pass

    def _maybe_roll_locked(self, now: float):
        if now - self._cur_start < self._window_s or not self._cur:
            return None
        win = {"start": self._cur_start, "end": now, "data": self._cur}
        self._ring.append(win)
        self._cur = {}
        self._cur_start = now
        return win

    def put_annexes(self, src: str, annexes: dict,
                    ts: float | None = None):
        """Latest-wins upsert of one pusher's annex set. The push
        replaces the pusher's whole set: keys it no longer publishes
        are dropped, so a retracted digest disappears on the next
        frame rather than lingering until max_age expiry."""
        now = ts if ts is not None else time.time()
        with self._lock:
            for k in [k for k in self._annex if k[0] == src]:
                if k[1] not in annexes:
                    del self._annex[k]
            for key, payload in annexes.items():
                self._annex[(src, key)] = (now, payload)

    def annexes(self, prefix: str = "",
                max_age_s: float | None = None) -> list:
        """[{src, key, ts, payload}] for keys under ``prefix``, newest
        first, dropping entries older than ``max_age_s``."""
        now = time.time()
        with self._lock:
            items = [(src, key, ts, payload)
                     for (src, key), (ts, payload) in self._annex.items()
                     if key.startswith(prefix)
                     and (max_age_s is None or now - ts <= max_age_s)]
        items.sort(key=lambda it: -it[2])
        return [{"src": src, "key": key, "ts": ts, "payload": payload}
                for src, key, ts, payload in items]

    # -- queries -------------------------------------------------------

    def names(self) -> dict:
        """{metric name: kind} over every window currently held."""
        out: dict = {}
        with self._lock:
            windows = list(self._ring) + [{"data": self._cur}]
        for win in windows:
            for name, ent in win["data"].items():
                out.setdefault(name, ent["kind"])
        return out

    def query(self, name: str, tags: dict | None = None,
              last_s: float | None = None, group_by=(),
              per_window: bool = False) -> dict:
        """Merge every window overlapping the last ``last_s`` seconds
        (all held windows when None). ``tags`` filters series by subset
        match; ``group_by`` names the tag keys results are grouped on
        (empty = one cluster-wide aggregate; ``["src"]`` = per pushing
        process). ``per_window`` returns the per-window series instead
        of one merged aggregate (range query for sparklines)."""
        now = time.time()
        cutoff = now - last_s if last_s else None
        tags = tags or {}
        group_by = tuple(group_by or ())
        with self._lock:
            # windows are TIME-based, so they must advance on queries
            # too: during a full metrics-plane partition nothing
            # ingests, and without this roll the pre-partition current
            # window would read as eternally fresh — consumers keying
            # freshness off the query horizon (the serve autoscaler's
            # degradation policy) would never see the data go stale
            rolled = self._maybe_roll_locked(now)
            windows = [dict(w) for w in self._ring]
            if self._cur:
                windows.append({"start": self._cur_start, "end": now,
                                "data": self._cur})
        if rolled is not None and self._on_roll is not None:
            try:
                self._on_roll(rolled)
            except Exception:  # noqa: BLE001 - publish is best-effort
                pass
        windows = [w for w in windows
                   if cutoff is None or w["end"] >= cutoff]
        kind = None
        boundaries = None
        for w in windows:
            ent = w["data"].get(name)
            if ent is not None:
                kind = ent["kind"]
                boundaries = ent.get("boundaries")
                break
        if kind is None:
            return {"name": name, "kind": None, "groups": [],
                    "windows": 0}

        def match(key: tuple) -> bool:
            kd = dict(key)
            return all(kd.get(k) == v for k, v in tags.items())

        def group_key(key: tuple) -> tuple:
            kd = dict(key)
            return tuple((g, kd.get(g, "")) for g in group_by)

        def merge_window(win) -> dict:
            groups: dict = {}
            ent = win["data"].get(name)
            if ent is None:
                return groups
            for key, payload in ent["series"].items():
                if not match(key):
                    continue
                g = group_key(key)
                if kind == "histogram":
                    groups[g] = _metrics.merge_hist(groups.get(g),
                                                    payload)
                elif kind == "gauge":
                    # gauges across sources sum (inflight-style
                    # gauges); per-source values come via group_by
                    groups[g] = groups.get(g, 0.0) + payload
                else:
                    groups[g] = groups.get(g, 0.0) + payload
            return groups

        out = {"name": name, "kind": kind, "boundaries": boundaries,
               "windows": len(windows),
               "from": min((w["start"] for w in windows), default=now),
               "to": max((w["end"] for w in windows), default=now)}
        if per_window:
            out["series"] = [
                {"start": w["start"], "end": w["end"],
                 "groups": [{"tags": dict(g), "value": v}
                            for g, v in merge_window(w).items()]}
                for w in windows]
            return out
        merged: dict = {}
        for w in windows:
            for g, v in merge_window(w).items():
                if kind == "histogram":
                    merged[g] = _metrics.merge_hist(merged.get(g), v)
                elif kind == "gauge":
                    merged[g] = v    # latest window wins for gauges
                else:
                    merged[g] = merged.get(g, 0.0) + v
        out["groups"] = [{"tags": dict(g), "value": v}
                         for g, v in merged.items()]
        return out


def summarize_histogram(result: dict,
                        quantiles=(0.5, 0.95, 0.99)) -> dict:
    """Client-side digest of one histogram query result (merged over
    every group): count, mean, and the requested quantiles."""
    boundaries = result.get("boundaries") or ()
    merged = None
    for g in result.get("groups", ()):
        if isinstance(g.get("value"), dict):
            merged = _metrics.merge_hist(merged, g["value"])
    if merged is None or merged["count"] <= 0:
        return {"count": 0}
    out = {"count": merged["count"],
           "mean": merged["sum"] / merged["count"]}
    for q in quantiles:
        out[f"p{int(q * 100)}"] = _metrics.quantile_from_buckets(
            boundaries, merged["buckets"], q)
    return out
