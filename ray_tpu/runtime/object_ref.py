"""ObjectRef: a future-like handle to a (possibly not yet computed) object.

Analog of the reference's ``ObjectRef`` (owned by the submitting worker; see
``src/ray/core_worker/reference_count.h``). Resolution goes through the active
runtime, so refs can be freely passed as task arguments (the runtime resolves
them before execution — same semantics as the reference's dependency
resolution in ``transport/dependency_resolver.cc``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ray_tpu.runtime import refcount as _refcount
from ray_tpu.runtime.refcount import global_counter as _refs
from ray_tpu.utils.ids import ObjectID

if TYPE_CHECKING:
    from ray_tpu.runtime.core import Runtime


class ObjectRef:
    __slots__ = ("_id", "_owner_hint", "_hex", "_tracked", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: str | None = None,
                 _track: bool = True):
        self._id = object_id
        self._owner_hint = owner_hint
        # distributed refcounting (reference: reference_count.h:61): every
        # live instance contributes to this process's local count; the
        # hex is cached so __del__ never touches the (possibly torn-down)
        # ObjectID during interpreter shutdown. ``_track=False`` opts
        # derived refs out (streaming item/end refs are minted and
        # dropped transiently during polling — counting them would free
        # live stream objects).
        self._hex = object_id.hex()
        # inactive process (no flusher / no local sink): never track, or
        # the counter's tables grow with nothing draining them
        self._tracked = _track and _refcount.is_active()
        if self._tracked:
            _refs.on_created(self._hex)

    def __del__(self):
        if self._tracked:
            _refs.on_destroyed(self._hex)

    @property
    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._hex

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        if not self._tracked:
            # untracked (stream-derived) refs stay untracked across
            # process boundaries — their lifecycle is LRU/eviction, not
            # refcounting
            return (_untracked_ref, (self._id, self._owner_hint))
        # serialization capture: a ref pickled inside a put value or a
        # task arg escapes this process — the active capture scope (see
        # refcount.RefCounter.capture) records it for contains-edge /
        # task-pin reporting
        _refs.note_serialized(self._hex)
        return (ObjectRef, (self._id, self._owner_hint))

    # Convenience: ref.get() / await-ability via the runtime.
    def get(self, timeout: float | None = None):
        from ray_tpu.runtime.core import get_runtime

        return get_runtime().get([self], timeout=timeout)[0]

    def future(self):
        """Return a concurrent.futures.Future resolved with the object value."""
        from ray_tpu.runtime.core import get_runtime

        return get_runtime().as_future(self)

    def __await__(self):
        import asyncio

        from ray_tpu.runtime.core import get_runtime

        return asyncio.wrap_future(get_runtime().as_future(self)).__await__()


def _untracked_ref(object_id: ObjectID, owner_hint: str | None = None):
    return ObjectRef(object_id, owner_hint, _track=False)
