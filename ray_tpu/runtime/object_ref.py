"""ObjectRef: a future-like handle to a (possibly not yet computed) object.

Analog of the reference's ``ObjectRef`` (owned by the submitting worker; see
``src/ray/core_worker/reference_count.h``). Resolution goes through the active
runtime, so refs can be freely passed as task arguments (the runtime resolves
them before execution — same semantics as the reference's dependency
resolution in ``transport/dependency_resolver.cc``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ray_tpu.utils.ids import ObjectID

if TYPE_CHECKING:
    from ray_tpu.runtime.core import Runtime


class ObjectRef:
    __slots__ = ("_id", "_owner_hint", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: str | None = None):
        self._id = object_id
        self._owner_hint = owner_hint

    @property
    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner_hint))

    # Convenience: ref.get() / await-ability via the runtime.
    def get(self, timeout: float | None = None):
        from ray_tpu.runtime.core import get_runtime

        return get_runtime().get([self], timeout=timeout)[0]

    def future(self):
        """Return a concurrent.futures.Future resolved with the object value."""
        from ray_tpu.runtime.core import get_runtime

        return get_runtime().as_future(self)

    def __await__(self):
        import asyncio

        from ray_tpu.runtime.core import get_runtime

        return asyncio.wrap_future(get_runtime().as_future(self)).__await__()
