"""Streaming generator tasks: ``num_returns="streaming"``.

Reference analog: ``StreamingObjectRefGenerator`` / ``ObjectRefGenerator``
(``python/ray/_raylet.pyx:252,267``) with ``num_returns="dynamic" |
"streaming"`` validated at ``_private/ray_option_utils.py:251-253``. A
generator task's yields become ObjectRefs that are consumable WHILE the
task is still running.

TPU-native design (no cross-process generator protocol): yield ``i`` of
task ``t`` is stored at a DETERMINISTICALLY derived object id
``H(t, i)`` — the consumer can mint the ref for any index without a
round trip, and readiness is the ordinary object-availability machinery
(local store seal, or GCS location + pull on remote nodes). End of
stream is a count object at ``H(t, END)``; it doubles as the task's
declared return id, so every existing failure path (lease break sealing
``return_oids``, cancellation, worker death) lands an exception exactly
where the consumer's end-of-stream check reads it.
"""

from __future__ import annotations

import hashlib
import struct

from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.utils.ids import ObjectID

_END_INDEX = -1


def _active_runtime():
    """The ambient runtime, bootstrapping the in-worker cluster client if
    this generator was shipped to a task/actor (same path as the public
    API's implicit init)."""
    from ray_tpu.api import _runtime

    return _runtime()


def stream_oid(task_id_bytes: bytes, index: int) -> ObjectID:
    """Derived object id for yield ``index`` of a streaming task
    (``_END_INDEX`` = the end-of-stream count object)."""
    h = hashlib.blake2b(
        task_id_bytes + struct.pack("<q", index),
        digest_size=ObjectID.SIZE, person=b"raystream")
    return ObjectID(h.digest())


def stream_end_ref(task_id_bytes: bytes) -> ObjectRef:
    # _track=False: these refs are minted transiently on every poll —
    # refcounting them would release live stream objects between polls
    # (stream object lifecycle stays LRU/eviction-managed)
    return ObjectRef(stream_oid(task_id_bytes, _END_INDEX), _track=False)


def stream_item_ref(task_id_bytes: bytes, index: int) -> ObjectRef:
    return ObjectRef(stream_oid(task_id_bytes, index), _track=False)


class ObjectRefGenerator:
    """Iterator over a streaming task's yields. ``__next__`` returns the
    next yield's ObjectRef as soon as that yield has been stored —
    ref-by-ref, while the task is still running — and raises
    StopIteration once the stream's count object says the task is done.

    Also usable with ``async for`` (``__anext__`` polls without blocking
    the event loop)."""

    def __init__(self, task_id_bytes: bytes):
        self._task_id = task_id_bytes
        self._next = 0
        self._length: int | None = None

    # -- pickling: consumers may be other tasks/actors -----------------
    def __reduce__(self):
        return (_rebuild_generator, (self._task_id, self._next))

    def __iter__(self):
        return self

    def _check_end(self, runtime) -> bool:
        """True once the stream length is known. Raises if the task
        failed (the failure is sealed into the end object)."""
        if self._length is not None:
            return True
        end = stream_end_ref(self._task_id)
        ready, _ = runtime.wait([end], num_returns=1, timeout=0)
        if not ready:
            return False
        self._length = runtime.get([end])[0]  # raises task errors
        return True

    def _poll(self, timeout: float):
        """One readiness probe; returns the next ref or None."""
        rt = _active_runtime()
        ref = stream_item_ref(self._task_id, self._next)
        ready, _ = rt.wait([ref], num_returns=1, timeout=timeout)
        if ready:
            self._next += 1
            return ref
        if self._check_end(rt) and self._next >= self._length:
            raise StopIteration
        return None

    def __next__(self) -> ObjectRef:
        while True:
            ref = self._poll(timeout=0.05)
            if ref is not None:
                return ref

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        while True:
            try:
                ref = self._poll(timeout=0)
            except StopIteration:
                raise StopAsyncIteration from None
            if ref is not None:
                return ref
            await asyncio.sleep(0.005)

    def completed(self) -> bool:
        try:
            return self._check_end(_active_runtime())
        except Exception:  # noqa: BLE001 - failed stream IS completed
            return True

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()}, next={self._next})"


def _rebuild_generator(task_id_bytes: bytes, next_index: int):
    g = ObjectRefGenerator(task_id_bytes)
    g._next = next_index
    return g


def store_stream(result, task_id_bytes: bytes, put_item, put_end):
    """Drive a generator task's iteration on the executing worker:
    ``put_item(oid_bytes, value, is_error)`` for each yield (sealed
    immediately — consumers see it while the task runs), then
    ``put_end(oid_bytes, count)``. A mid-stream exception is sealed as
    the NEXT yield (the consumer raises it on that ``next()``) and the
    stream is closed after it."""
    index = 0
    try:
        for value in result:
            put_item(stream_oid(task_id_bytes, index).binary(), value,
                     False)
            index += 1
    except BaseException as e:  # noqa: BLE001 - sealed for the consumer
        put_item(stream_oid(task_id_bytes, index).binary(), e, True)
        index += 1
    put_end(stream_oid(task_id_bytes, _END_INDEX).binary(), index)
