"""The in-worker runtime: submission, ownership, scheduling, execution.

Local-mode analog of the reference's core_worker + raylet pair
(``src/ray/core_worker/core_worker.cc`` + ``src/ray/raylet/``): a dependency
manager gates tasks on their arguments (reference
``transport/dependency_resolver.cc``), a dispatcher accounts resources and
hands ready tasks to a worker pool (reference ``LocalTaskManager``), actors
get dedicated ordered execution queues (reference
``DirectActorTaskSubmitter`` + ``ActorSchedulingQueue``), and failures flow
through retry bookkeeping (reference ``TaskManager::RetryTaskIfPossible``,
``task_manager.h:369``).

Execution here is thread-based (one process); the cluster backend swaps the
executor layer for multiprocess workers while reusing this scheduling core.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.object_store import ObjectStore
from ray_tpu.runtime.task_spec import ResourceSet, TaskSpec, TaskType
from ray_tpu.utils import exceptions as exc
from ray_tpu.utils.config import Config, get_config
from ray_tpu.utils.ids import ActorID, JobID, NodeID, ObjectID, TaskID, _Counter


def _isawaitable(x) -> bool:
    import inspect

    return inspect.isawaitable(x)


# ---------------------------------------------------------------------------
# Actor bookkeeping
# ---------------------------------------------------------------------------

@dataclass
class ActorState:
    actor_id: ActorID
    name: str | None
    namespace: str = "default"
    instance: Any = None
    dead: bool = False
    death_reason: str = ""
    max_restarts: int = 0
    num_restarts: int = 0
    creation_spec: TaskSpec | None = None
    # Ordered execution: a dedicated single-thread (or N-thread) executor.
    executor: ThreadPoolExecutor | None = None
    lock: threading.Lock = field(default_factory=threading.Lock)
    # In-order dispatch (reference: SequentialActorSubmitQueue +
    # ActorSchedulingQueue): tasks are sequenced at submission and dispatched
    # to the executor strictly in sequence order, even if an earlier call's
    # argument dependencies resolve after a later call's.
    submit_seq: _Counter = field(default_factory=_Counter)
    next_to_dispatch: int = 1
    seq_buffer: dict[int, TaskSpec] = field(default_factory=dict)
    # Tasks handed to the executor but not yet completed (for kill cleanup).
    in_flight: dict[TaskID, TaskSpec] = field(default_factory=dict)
    # ASYNC actors: concurrency bound for coroutine methods scheduled on
    # the runtime's shared event loop (created lazily on that loop)
    async_sem: Any = None


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------

# Serializes tasks that declare env_vars (reference: each runtime_env
# gets its own worker PROCESS — worker_pool.cc env-keyed caching; the
# in-process local runtime approximates that by scoping os.environ
# mutations under one lock so concurrent tasks never see each other's
# vars half-applied). The lock is SUSPENDED while its holder blocks in
# get()/wait() (see _note_worker_blocked) — otherwise a task with
# env_vars waiting on a child that also has env_vars deadlocks.
_runtime_env_lock = threading.Lock()


class _EnvVarSession:
    """One task execution's os.environ overlay; suspendable."""

    def __init__(self, env_vars: dict):
        self.env_vars = env_vars
        self.old: dict | None = None
        self.held = False

    def acquire(self):
        _runtime_env_lock.acquire()
        self.held = True
        self.old = {k: os.environ.get(k) for k in self.env_vars}
        os.environ.update(self.env_vars)

    def release(self):
        if not self.held:
            return
        for k, v in (self.old or {}).items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        self.held = False
        _runtime_env_lock.release()


class Runtime:
    """Singleton runtime: object store + scheduler + actor registry."""

    def __init__(self, config: Config | None = None,
                 resources: dict | None = None,
                 namespace: str | None = None):
        self.config = config or get_config()
        self.job_id = JobID.from_random()
        # named actors scope to a namespace; default = this job's id
        # (reference: worker.py:1157 — anonymous namespaces isolate jobs)
        self.namespace = namespace or f"job-{self.job_id.hex()[:12]}"
        self.node_id = NodeID.from_random()
        self.store = ObjectStore()
        self._task_counter = _Counter()

        # --- resource accounting (reference: LocalResourceManager) ---
        ncpu = float(os.cpu_count() or 1)
        self.total_resources: dict[str, float] = {"CPU": ncpu, "memory": 0.0}
        if resources:
            self.total_resources.update({k: float(v) for k, v in resources.items()})
        self.available_resources = dict(self.total_resources)
        self._res_lock = threading.Lock()
        self._res_cv = threading.Condition(self._res_lock)

        # --- dependency manager ---
        self._dep_lock = threading.Lock()
        # object id -> list of task specs blocked on it
        self._waiting_on: dict[ObjectID, list[TaskSpec]] = {}
        # task id -> set of unresolved dep ids
        self._unresolved: dict[TaskID, set[ObjectID]] = {}
        self.store.subscribe_put(self._on_object_available)

        # --- dispatch queue + worker pool ---
        # Size from the CPU *resource* (the logical cluster), not just host
        # cores: init(num_cpus=8) on a 4-core host must still run 8
        # concurrent tasks (reference: worker pool scales with resource
        # demand, not cores — worker_pool.cc prestart).
        nworkers = self.config.num_workers or int(
            max(ncpu, self.total_resources.get("CPU", 0.0)))
        self._ready: deque[TaskSpec] = deque()
        self._ready_cv = threading.Condition()
        # Feasible-but-busy tasks parked until resources free up (reference:
        # LocalTaskManager's waiting queue; avoids head-of-line blocking).
        self._blocked: deque[TaskSpec] = deque()
        # Future waiters keyed by object id (as_future resolution, threadless).
        self._future_waiters: dict[ObjectID, list[Future]] = {}
        self._base_workers = max(4, nworkers)
        self._pool = ThreadPoolExecutor(
            max_workers=self._base_workers,
            thread_name_prefix="ray_tpu-worker",
        )
        # Blocked-worker relief (reference: a worker blocked in ray.get
        # releases its slot so the raylet can lease new workers —
        # worker_pool prestart on blocked leases). Pool threads blocked in
        # get() keep occupying their thread, so the dispatcher runs tasks
        # on overflow threads whenever every pool thread is taken, up to a
        # cap. Without this, N tasks that all wait on a child task/actor
        # deadlock an N-thread pool.
        self._pool_cap = max(64, 4 * self._base_workers)
        self._thread_acct = threading.Lock()
        self._inflight_pool = 0      # submitted to pool, not yet finished
        self._overflow_threads = 0   # live overflow threads
        # Per-worker-thread execution state (current spec, block depth)
        # used by the blocked-worker protocol above.
        self._exec_tl = threading.local()
        self._shutdown = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="ray_tpu-dispatcher", daemon=True
        )
        self._dispatcher.start()

        # --- actors ---
        self._actors: dict[ActorID, ActorState] = {}
        self._named_actors: dict[str, ActorID] = {}
        self._actor_lock = threading.Lock()

        # --- cancellation ---
        self._cancelled: set[TaskID] = set()
        self._return_owner: dict[ObjectID, TaskID] = {}

        # --- observability (reference: TaskEventBuffer) ---
        self.metrics = {
            "tasks_submitted": _Counter(),
            "tasks_finished": _Counter(),
            "tasks_failed": _Counter(),
            "tasks_retried": _Counter(),
            "actors_created": _Counter(),
        }
        # bounded task timeline (reference: task_event_buffer.cc →
        # ray.timeline() chrome-trace export)
        self._task_events: deque = deque(maxlen=10000)

        # --- reference counting (local mode: immediate in-process
        # release — the cluster protocol's GCS half collapses to a
        # store.free call; see runtime/refcount.py) ---
        from ray_tpu.runtime.refcount import global_counter as _refs
        self._refs = _refs
        self._ref_enabled = self.config.ref_counting_enabled
        # released-before-created oids (fire-and-forget returns): freed
        # the moment the producing task stores them
        self._released_oids: set[ObjectID] = set()
        if self._ref_enabled:
            self._refs.set_local_release(self._on_ref_zero)
            threading.Thread(target=self._ref_poll_loop, daemon=True,
                             name="ref-poller").start()

    def _ref_poll_loop(self):
        while not self._shutdown:
            time.sleep(0.05)
            self._refs.poll_local()

    def _on_ref_zero(self, oid_hex: str):
        """No live ObjectRef instance anywhere in this process: free the
        stored value (or arrange free-on-arrival for a result whose task
        is still running — fire-and-forget returns). Marked BEFORE the
        store check: an object arriving in between is caught by either
        this free or _on_object_available's released check (free is
        idempotent; both sides discard the mark)."""
        oid = ObjectID.from_hex(oid_hex)
        self._released_oids.add(oid)
        while len(self._released_oids) > 1_000_000:
            self._released_oids.pop()
        if self.store.contains(oid):
            self._released_oids.discard(oid)
            self.store.free([oid])

    def record_task_event(self, spec: TaskSpec, start: float, end: float,
                          ok: bool):
        # start/end are monotonic (caller's clock); wall_* anchors them
        # to the wall clock HERE, while the monotonic domain is still
        # ours — wall stamps are what makes events comparable across
        # processes and with tracing spans (one trace file, one clock)
        offset = time.time() - time.monotonic()
        self._task_events.append({
            "task_id": spec.task_id.hex(),
            "name": spec.function_name,
            "start": start,
            "end": end,
            "wall_start": start + offset,
            "wall_end": end + offset,
            "pid": os.getpid(),
            "state": "FINISHED" if ok else "FAILED",
            "thread": threading.current_thread().name,
        })

    def task_events(self, limit: int = 1000) -> list:
        events = list(self._task_events)
        return events[-limit:]

    # ------------------------------------------------------------------
    # Public object API
    # ------------------------------------------------------------------

    def put(self, value) -> ObjectRef:
        oid = ObjectID.from_random()
        self.store.put(oid, value)
        return ObjectRef(oid)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        ids = [r.id for r in refs]
        blocked = any(not self.store.contains(i) for i in ids)
        if blocked:
            self._note_worker_blocked()
        try:
            return self.store.get(ids, timeout=timeout)
        finally:
            if blocked:
                self._note_worker_unblocked()

    def _call_in_runtime_env(self, runtime_env, fn, args, kwargs):
        if not runtime_env:
            return fn(*args, **kwargs)
        from ray_tpu.runtime_env import apply_paths

        apply_paths(runtime_env)
        env_vars = runtime_env.get("env_vars")
        if not env_vars:
            return fn(*args, **kwargs)
        tl = self._exec_tl
        session = _EnvVarSession(env_vars)
        prev = getattr(tl, "env_session", None)
        tl.env_session = session
        session.acquire()
        try:
            return fn(*args, **kwargs)
        finally:
            session.release()
            tl.env_session = prev

    def _submit_to_workers(self, spec: TaskSpec):
        """Run a ready task on the pool, or on an overflow thread when
        every pool thread is taken (busy OR parked in a blocking get —
        either way the thread is occupied). Uses only public executor
        API; overflow is bounded by _pool_cap."""
        with self._thread_acct:
            overflow = (
                self._inflight_pool >= self._base_workers
                and (self._base_workers + self._overflow_threads)
                < self._pool_cap
            )
            if overflow:
                self._overflow_threads += 1
            else:
                self._inflight_pool += 1
        if overflow:
            threading.Thread(
                target=self._execute_overflow, args=(spec,),
                name="ray_tpu-worker-overflow", daemon=True,
            ).start()
        else:
            self._pool.submit(self._execute_pooled, spec)

    def _execute_pooled(self, spec: TaskSpec):
        try:
            self._execute_task(spec)
        finally:
            with self._thread_acct:
                self._inflight_pool -= 1

    def _execute_overflow(self, spec: TaskSpec):
        try:
            self._execute_task(spec)
        finally:
            with self._thread_acct:
                self._overflow_threads -= 1

    def _note_worker_blocked(self):
        """This thread is about to block on objects produced by other
        tasks (reference analog: a worker blocked in ray.get releases its
        lease so the raylet can run other work). Suspends the thread's
        env-var session (any thread) and releases the blocked task's
        acquired resources (pool worker threads). Thread availability is
        handled at dispatch time by _submit_to_workers' overflow
        threads."""
        tl = self._exec_tl
        depth = getattr(tl, "block_depth", 0)
        tl.block_depth = depth + 1
        if depth == 0:
            sess = getattr(tl, "env_session", None)
            if sess is not None and sess.held:
                sess.release()
                tl.env_suspended = True
        if not threading.current_thread().name.startswith("ray_tpu-worker"):
            return
        spec = getattr(tl, "spec", None)
        if (depth == 0 and spec is not None
                and not spec.resources.is_empty()):
            tl.released_resources = True
            self._release_resources(spec.resources)

    def _note_worker_unblocked(self):
        """Re-acquire the task's resources and env session on wake. May
        transiently oversubscribe (available goes negative) — same trade
        the reference makes when a blocked worker resumes; it
        self-corrects when the task finishes and releases."""
        tl = self._exec_tl
        depth = getattr(tl, "block_depth", 1) - 1
        tl.block_depth = depth
        if depth == 0 and getattr(tl, "env_suspended", False):
            tl.env_suspended = False
            sess = getattr(tl, "env_session", None)
            if sess is not None:
                sess.acquire()
        if not threading.current_thread().name.startswith("ray_tpu-worker"):
            return
        spec = getattr(tl, "spec", None)
        if (depth == 0 and getattr(tl, "released_resources", False)
                and spec is not None):
            tl.released_resources = False
            with self._res_cv:
                for k, v in spec.resources.resources.items():
                    self.available_resources[k] = (
                        self.available_resources.get(k, 0.0) - v)

    def wait(self, refs: list[ObjectRef], num_returns=1, timeout=None):
        # Same blocked-worker protocol as get(): a worker parked in
        # wait() must release its resources or children deadlock.
        present = sum(self.store.contains(r.id) for r in refs)
        blocked = present < num_returns
        if blocked:
            self._note_worker_blocked()
        try:
            ready_ids, not_ready_ids = self.store.wait(
                [r.id for r in refs], num_returns, timeout
            )
        finally:
            if blocked:
                self._note_worker_unblocked()
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in not_ready_ids]

    def as_future(self, ref: ObjectRef) -> Future:
        """Threadless future: resolved by the store's put notification."""
        fut: Future = Future()
        with self._dep_lock:
            found, value, is_error = self.store.get_entry(ref.id)
            if not found:
                self._future_waiters.setdefault(ref.id, []).append(fut)
                return fut
        if is_error:
            fut.set_exception(value)
        else:
            fut.set_result(value)
        return fut

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        # Infeasible demands fail fast (the reference surfaces these to the
        # autoscaler; with a fixed local cluster they can never be satisfied).
        if not spec.resources.fits_in(self.total_resources):
            raise ValueError(
                f"Task {spec.function_name!r} requires "
                f"{spec.resources.resources}, which exceeds cluster capacity "
                f"{self.total_resources}"
            )
        streaming = spec.num_returns in ("streaming", "dynamic")
        if streaming:
            # the end-of-stream count object IS the declared return id:
            # every failure path that seals return_ids lands where the
            # consumer's end check reads (see runtime/streaming.py)
            from ray_tpu.runtime.streaming import (ObjectRefGenerator,
                                                   stream_end_ref)
            spec.return_ids = [stream_end_ref(spec.task_id.binary()).id]
        else:
            spec.return_ids = [ObjectID.from_random()
                               for _ in range(spec.num_returns)]
        spec.submitted_at = time.monotonic()
        if spec.task_type == TaskType.ACTOR_TASK:
            state = self._actors.get(spec.actor_id)
            if state is not None:
                spec.sequence_number = state.submit_seq.next()
        self.metrics["tasks_submitted"].next()
        self._resolve_or_queue(spec)
        if streaming:
            return [ObjectRefGenerator(spec.task_id.binary())]
        return [ObjectRef(oid) for oid in spec.return_ids]

    def _task_dependencies(self, spec: TaskSpec) -> set[ObjectID]:
        deps: set[ObjectID] = set()
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef) and not self.store.contains(a.id):
                deps.add(a.id)
        return deps

    def _resolve_or_queue(self, spec: TaskSpec):
        deps = self._task_dependencies(spec)
        if not deps:
            self._mark_ready(spec)
            return
        with self._dep_lock:
            # Re-check under the lock: objects may have landed meanwhile.
            deps = {d for d in deps if not self.store.contains(d)}
            if not deps:
                pass
            else:
                self._unresolved[spec.task_id] = deps
                for d in deps:
                    self._waiting_on.setdefault(d, []).append(spec)
                return
        self._mark_ready(spec)

    def _on_object_available(self, oid: ObjectID):
        newly_ready: list[TaskSpec] = []
        with self._dep_lock:
            for spec in self._waiting_on.pop(oid, []):
                pending = self._unresolved.get(spec.task_id)
                if pending is None:
                    continue
                pending.discard(oid)
                if not pending:
                    del self._unresolved[spec.task_id]
                    newly_ready.append(spec)
            waiters = self._future_waiters.pop(oid, [])
        for spec in newly_ready:
            self._mark_ready(spec)
        if waiters:
            found, value, is_error = self.store.get_entry(oid)
            for fut in waiters:
                if not found:
                    continue
                if is_error:
                    fut.set_exception(value)
                else:
                    fut.set_result(value)
        if oid in self._released_oids:
            # every reference was dropped before the producing task
            # finished: free on arrival (futures above resolved first);
            # discard-then-free mirrors _on_ref_zero so the concurrent
            # paths converge on exactly one (idempotent) free
            self._released_oids.discard(oid)
            self.store.free([oid])

    def _mark_ready(self, spec: TaskSpec):
        if spec.task_type == TaskType.ACTOR_TASK:
            self._dispatch_actor_task(spec)
            return
        with self._ready_cv:
            self._ready.append(spec)
            self._ready_cv.notify()

    # ------------------------------------------------------------------
    # Dispatcher (reference: LocalTaskManager::ScheduleAndDispatchTasks)
    # ------------------------------------------------------------------

    def _dispatch_loop(self):
        """Dispatch ready tasks that fit in available resources; park the rest
        (no head-of-line blocking — a busy big task must not starve small
        ones, and resource waits must not deadlock dependent chains)."""
        while True:
            with self._ready_cv:
                while not self._ready and not self._shutdown:
                    self._ready_cv.wait(timeout=0.5)
                if self._shutdown:
                    return
                spec = self._ready.popleft()
            if self._try_acquire(spec.resources):
                if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    # Dedicated thread: creation is on the critical path of
                    # every queued method call (callers block on it), so it
                    # must never starve behind long tasks in the pool. The
                    # "ray_tpu-worker" prefix opts it into the
                    # blocked-worker protocol (a blocking __init__ must
                    # release its resources too).
                    threading.Thread(
                        target=self._execute_task, args=(spec,),
                        name="ray_tpu-worker-actor-creation", daemon=True,
                    ).start()
                else:
                    self._submit_to_workers(spec)
            else:
                with self._res_cv:
                    self._blocked.append(spec)

    def _try_acquire(self, rs: ResourceSet) -> bool:
        if rs.is_empty():
            return True
        with self._res_cv:
            if not rs.fits_in(self.available_resources):
                return False
            for k, v in rs.resources.items():
                self.available_resources[k] = self.available_resources.get(k, 0.0) - v
            return True

    def _release_resources(self, rs: ResourceSet):
        if rs.is_empty():
            return
        unparked: list[TaskSpec] = []
        with self._res_cv:
            for k, v in rs.resources.items():
                self.available_resources[k] = self.available_resources.get(k, 0.0) + v
            unparked = list(self._blocked)
            self._blocked.clear()
            self._res_cv.notify_all()
        if unparked:
            with self._ready_cv:
                self._ready.extend(unparked)
                self._ready_cv.notify()

    # ------------------------------------------------------------------
    # Execution (reference: _raylet.pyx execute_task)
    # ------------------------------------------------------------------

    def _materialize_args(self, spec: TaskSpec):
        args = [
            self.store.get([a.id])[0] if isinstance(a, ObjectRef) else a
            for a in spec.args
        ]
        kwargs = {
            k: self.store.get([v.id])[0] if isinstance(v, ObjectRef) else v
            for k, v in spec.kwargs.items()
        }
        return args, kwargs

    def _store_results(self, spec: TaskSpec, result):
        if spec.num_returns in ("streaming", "dynamic"):
            from ray_tpu.runtime.streaming import store_stream

            store_stream(
                result, spec.task_id.binary(),
                lambda oid, v, er: self.store.put(ObjectID(oid), v,
                                                  is_error=er),
                lambda oid, n: self.store.put(ObjectID(oid), n))
            self._task_done(spec)
            return
        try:
            if spec.num_returns == 1:
                self.store.put(spec.return_ids[0], result)
            else:
                values = list(result)  # may raise on non-iterable results
                if len(values) != spec.num_returns:
                    raise ValueError(
                        f"Task declared num_returns={spec.num_returns} but "
                        f"returned {len(values)} values"
                    )
                for oid, v in zip(spec.return_ids, values):
                    self.store.put(oid, v)
        except BaseException as e:  # noqa: BLE001 - must never lose return ids
            self._store_error(spec, exc.TaskError(spec.function_name, e))
            return
        self._task_done(spec)

    def _store_error(self, spec: TaskSpec, error: BaseException):
        for oid in spec.return_ids:
            self.store.put(oid, error, is_error=True)
        self._task_done(spec)

    def _task_done(self, spec: TaskSpec):
        """Completion bookkeeping: drop per-task tracking state so long-running
        drivers don't leak (one entry per task otherwise)."""
        for oid in spec.return_ids:
            self._return_owner.pop(oid, None)
        self._cancelled.discard(spec.task_id)
        if spec.actor_id is not None:
            state = self._actors.get(spec.actor_id)
            if state is not None:
                state.in_flight.pop(spec.task_id, None)

    def _execute_task(self, spec: TaskSpec):
        if spec.task_id in self._cancelled:
            self._release_resources(spec.resources)
            self._store_error(spec, exc.TaskCancelledError(spec.task_id))
            return
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # Creation holds its resources for the actor's lifetime; release
            # happens in kill_actor / creation-failure, not here.
            self._execute_actor_creation(spec)
            return
        started = time.monotonic()
        self._exec_tl.spec = spec
        try:
            try:
                args, kwargs = self._materialize_args(spec)
            except BaseException as e:  # dep failed -> propagate as task error
                self.metrics["tasks_failed"].next()
                self._store_error(spec, e)
                return
            try:
                from ray_tpu.util import tracing as _tracing

                with _tracing.execution_span(spec.function_name,
                                             spec.trace_ctx), \
                        _tracing.inflight("task", spec.function_name):
                    result = self._call_in_runtime_env(
                        spec.runtime_env, spec.function, args, kwargs)
                    if _isawaitable(result):
                        result = self._await_on_loop(result)
            except BaseException as e:  # noqa: BLE001
                if spec.max_retries > 0 and spec.retry_exceptions:
                    spec.max_retries -= 1
                    self.metrics["tasks_retried"].next()
                    self._resolve_or_queue(spec)
                    return
                self.metrics["tasks_failed"].next()
                self.record_task_event(spec, started, time.monotonic(), False)
                self._store_error(spec, exc.TaskError(spec.function_name, e))
                return
            self._store_results(spec, result)
            self.metrics["tasks_finished"].next()
            self.record_task_event(spec, started, time.monotonic(), True)
        finally:
            self._exec_tl.spec = None
            self._release_resources(spec.resources)

    # ------------------------------------------------------------------
    # Actors (reference: GcsActorManager + DirectActorTaskSubmitter)
    # ------------------------------------------------------------------

    def _effective_namespace(self, override: str | None = None) -> str:
        if override:
            return override
        from ray_tpu.runtime_context import current_task_namespace

        return current_task_namespace() or self.namespace

    def create_actor(self, spec: TaskSpec, name: str | None = None,
                     namespace: str | None = None,
                     lifetime: str | None = None) -> ActorID:
        # ``lifetime`` is owner-scoped in cluster mode; in local mode the
        # owner IS this process, so every actor dies with it either way.
        actor_id = ActorID.from_random()
        spec.actor_id = actor_id
        ns = self._effective_namespace(namespace)
        state = ActorState(
            actor_id=actor_id,
            name=name,
            namespace=ns,
            max_restarts=spec.max_restarts,
            creation_spec=spec,
        )
        state.executor = ThreadPoolExecutor(
            max_workers=max(1, spec.max_concurrency),
            thread_name_prefix=f"actor-{actor_id.hex()[:8]}",
        )
        with self._actor_lock:
            if name is not None:
                # registry key carries the namespace (same convention as
                # the GCS registry, runtime/gcs.py:_ns_key); state.name
                # stays the bare user-visible name
                key = f"{ns}\x1f{name}"
                if key in self._named_actors:
                    raise ValueError(
                        f"Actor name {name!r} already taken in namespace "
                        f"{ns!r}")
                self._named_actors[key] = actor_id
            self._actors[actor_id] = state
        self.metrics["actors_created"].next()
        self._resolve_or_queue(spec)  # creation waits on arg deps like any task
        return actor_id

    def _execute_actor_creation(self, spec: TaskSpec):
        # NOTE: actor resources are held for the actor's LIFETIME (released in
        # kill_actor/shutdown), matching the reference's lease semantics — not
        # released when __init__ returns.
        state = self._actors[spec.actor_id]
        # Opt into the blocked-worker protocol: a __init__ that blocks in
        # get() must release the actor's held resources while it waits.
        self._exec_tl.spec = spec
        try:
            args, kwargs = self._materialize_args(spec)
            cls = spec.function
            instance = self._call_in_runtime_env(
                spec.runtime_env, cls, args, kwargs)
        except BaseException as e:  # noqa: BLE001
            state.dead = True
            state.death_reason = f"__init__ failed: {e!r}"
            self._release_resources(spec.resources)
            self._store_error(
                spec, exc.ActorDiedError(spec.actor_id, state.death_reason)
            )
            self._fail_pending_actor_tasks(state)
            return
        finally:
            self._exec_tl.spec = None
        with state.lock:
            state.instance = instance
        # Creation "return" marks readiness (reference: actor creation task
        # return signals schedulability of queued method calls).
        self._store_results(spec, None)

    def _dispatch_actor_task(self, spec: TaskSpec):
        """Buffer by sequence number; dispatch strictly in submission order
        (reference: SequentialActorSubmitQueue). An early call whose arg deps
        resolve late must still run before later calls on the same actor."""
        state = self._actors.get(spec.actor_id)
        if state is None or state.dead:
            reason = state.death_reason if state else "unknown actor"
            self._store_error(spec, exc.ActorDiedError(spec.actor_id, reason))
            return
        with state.lock:
            state.seq_buffer[spec.sequence_number] = spec
            runnable = []
            while state.next_to_dispatch in state.seq_buffer:
                s = state.seq_buffer.pop(state.next_to_dispatch)
                state.next_to_dispatch += 1
                state.in_flight[s.task_id] = s
                runnable.append(s)
        for s in runnable:
            state.executor.submit(self._execute_actor_task, state, s)

    def _fail_pending_actor_tasks(self, state: ActorState):
        """Store ActorDiedError for every queued/buffered call so get() never
        hangs on a killed actor's in-flight results."""
        with state.lock:
            buffered = list(state.seq_buffer.values())
            state.seq_buffer.clear()
            in_flight = list(state.in_flight.values())
            state.in_flight.clear()
        err_specs = buffered + in_flight
        for s in err_specs:
            # Store.put is first-write-wins: if the task already completed,
            # this is a no-op; otherwise consumers observe the death.
            self._store_error(
                s, exc.ActorDiedError(state.actor_id, state.death_reason)
            )

    def _execute_actor_task(self, state: ActorState, spec: TaskSpec):
        if spec.task_id in self._cancelled:
            self._store_error(spec, exc.TaskCancelledError(spec.task_id))
            return
        if state.dead:
            self._store_error(
                spec, exc.ActorDiedError(state.actor_id, state.death_reason)
            )
            return
        # Wait for __init__ to finish (creation task runs on the main pool).
        while state.instance is None and not state.dead:
            time.sleep(0.001)
        if state.dead:
            self._store_error(
                spec, exc.ActorDiedError(state.actor_id, state.death_reason)
            )
            return
        try:
            args, kwargs = self._materialize_args(spec)
            method = getattr(state.instance, spec.actor_method_name)
            renv = (state.creation_spec.runtime_env
                    if state.creation_spec is not None else None)
            from ray_tpu.util import tracing as _tracing

            with _tracing.execution_span(spec.function_name,
                                         spec.trace_ctx), \
                    _tracing.inflight("actor_task", spec.function_name):
                result = self._call_in_runtime_env(renv, method, args,
                                                   kwargs)
                if _isawaitable(result):
                    # ASYNC actor method: schedule the coroutine on the
                    # shared event loop and RETURN the pool thread
                    # immediately — awaits overlap up to max_concurrency
                    # (semaphore), and quick sync methods (metrics,
                    # pings) keep running on free pool threads instead
                    # of queueing behind slow requests (reference:
                    # fibers, core_worker/fiber.h:17)
                    self._spawn_actor_coro(state, spec, result)
                    return
        except BaseException as e:  # noqa: BLE001
            self.metrics["tasks_failed"].next()
            self._store_error(
                spec, exc.TaskError(f"{spec.function_name}", e)
            )
            return
        self._store_results(spec, result)
        self.metrics["tasks_finished"].next()

    def _ensure_async_loop(self):
        import asyncio

        with self._actor_lock:
            loop = getattr(self, "_async_loop", None)
            if loop is None:
                loop = asyncio.new_event_loop()
                self._async_loop = loop
                threading.Thread(target=loop.run_forever, daemon=True,
                                 name="runtime-asyncio-loop").start()
        return loop

    def _await_on_loop(self, awaitable):
        """Run an awaitable to completion on the runtime's shared event
        loop (started lazily), blocking the calling pool thread."""
        import asyncio

        loop = self._ensure_async_loop()

        async def drive():
            return await awaitable

        return asyncio.run_coroutine_threadsafe(drive(), loop).result()

    def _spawn_actor_coro(self, state: ActorState, spec: TaskSpec,
                          awaitable):
        """Fire an async actor call onto the shared loop (non-blocking);
        results/errors are stored from the loop when it finishes."""
        import asyncio

        loop = self._ensure_async_loop()
        if state.async_sem is None:
            # under the lock: two pool threads dispatching concurrently
            # must share ONE semaphore or max_concurrency isn't enforced
            with self._actor_lock:
                if state.async_sem is None:
                    mc = (state.creation_spec.max_concurrency
                          if state.creation_spec is not None else 1)
                    state.async_sem = asyncio.Semaphore(max(1, int(mc or 1)))

        async def drive():
            async with state.async_sem:
                try:
                    result = await awaitable
                except BaseException as e:  # noqa: BLE001
                    self.metrics["tasks_failed"].next()
                    self._store_error(
                        spec, exc.TaskError(f"{spec.function_name}", e))
                    return
                self._store_results(spec, result)
                self.metrics["tasks_finished"].next()

        asyncio.run_coroutine_threadsafe(drive(), loop)

    def get_actor(self, name: str, namespace: str | None = None) -> ActorID:
        key = f"{self._effective_namespace(namespace)}\x1f{name}"
        with self._actor_lock:
            if key not in self._named_actors:
                raise ValueError(f"Failed to look up actor with name {name!r}")
            return self._named_actors[key]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self._actor_lock:
            state = self._actors.get(actor_id)
            if state is None:
                return
            already_dead = state.dead
            state.dead = True
            state.death_reason = "killed via kill()"
            if state.name:
                self._named_actors.pop(
                    f"{state.namespace}\x1f{state.name}", None)
        if already_dead:
            return
        if state.executor:
            state.executor.shutdown(wait=False, cancel_futures=True)
        self._fail_pending_actor_tasks(state)
        if state.creation_spec is not None:
            self._release_resources(state.creation_spec.resources)

    def actor_state(self, actor_id: ActorID) -> ActorState | None:
        return self._actors.get(actor_id)

    # ------------------------------------------------------------------
    # Cancellation
    # ------------------------------------------------------------------

    def free(self, refs: list):
        """Release stored objects (reference: ray.internal.free)."""
        self.store.free([r.id for r in refs])

    def cancel(self, ref: ObjectRef, force: bool = False):
        # Best-effort: mark every task whose return id matches. Local mode
        # cannot interrupt a running Python frame (same caveat as the
        # reference for non-async actors); queued tasks fail fast.
        # Find the owning spec lazily: we track via return-id -> task map.
        tid = self._return_owner.get(ref.id)
        if tid is not None:
            self._cancelled.add(tid)

    def note_return_owner(self, spec: TaskSpec):
        for oid in spec.return_ids:
            self._return_owner[oid] = spec.task_id

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def shutdown(self):
        self._shutdown = True
        if self._ref_enabled:
            self._refs.set_local_release(None)
            self._refs.reset()
        with self._ready_cv:
            self._ready_cv.notify_all()
        with self._res_cv:
            self._res_cv.notify_all()
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._actor_lock:
            actors = list(self._actors.values())
            self._actors.clear()
            self._named_actors.clear()
        for state in actors:
            state.dead = True
            state.death_reason = "runtime shutdown"
            if state.executor:
                state.executor.shutdown(wait=False, cancel_futures=True)
            self._fail_pending_actor_tasks(state)

    def cluster_resources(self) -> dict:
        return dict(self.total_resources)

    def available_resources_snapshot(self) -> dict:
        with self._res_lock:
            return dict(self.available_resources)


# ---------------------------------------------------------------------------
# Global runtime management
# ---------------------------------------------------------------------------

_runtime: Runtime | None = None
_runtime_lock = threading.Lock()


def get_runtime() -> Runtime:
    global _runtime
    if _runtime is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first."
        )
    return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def init_runtime(config: Config | None = None,
                 resources: dict | None = None,
                 namespace: str | None = None) -> Runtime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = Runtime(config=config, resources=resources,
                               namespace=namespace)
        return _runtime


def install_runtime(runtime) -> None:
    """Install an externally constructed runtime (cluster mode: the
    ``driver.ClusterRuntime`` duck-types ``Runtime``)."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            raise RuntimeError("a runtime is already initialized")
        _runtime = runtime


def shutdown_runtime():
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
