"""Cluster log plane: captured process output, task attribution, and
the GCS-side log store.

Reference analog: the per-worker log files under the session dir plus
``log_monitor.py`` tailing them into GCS pubsub and the dashboard, with
the driver echoing ``(actor pid=...)``-prefixed lines. Four cooperating
pieces live here; the transport glue lives in the runtime modules:

- **Capture** — :func:`install_capture` replaces ``sys.stdout``/
  ``sys.stderr`` with a line-buffered tee: every complete line is
  stamped ``(proc, pid, ts)`` plus the ambient trace/task context and
  appended to a rotating ``<proc>.log`` under the node's log dir
  (bounds: ``RAY_TPU_LOG_MAX_BYTES`` / ``RAY_TPU_LOG_ROTATE_COUNT``).
  The raw Popen fd redirect to ``<proc>.out/.err`` stays in place
  underneath — interpreter-level crashes bypass Python streams, and
  their last words must land somewhere the monitor can find.
- **Attribution** — :func:`task_context` brackets each task/actor-method
  execution with begin/end byte offsets, producing a bounded
  ``task_id -> (file, start, end)`` segment registry published as a
  metric annex (``logs/segments/<proc>``) riding the process's
  MetricsPusher frames; ``get_log(task_id=...)`` resolves through it
  and serves exactly that segment.
- **Store** — :class:`LogStore` on the GCS keeps a bounded per-process
  ring plus a global error ring with deduplicated error GROUPS
  (signature-normalized, counts + first/last seen + linked trace ids).
  Ingest dedups by (file, offset) watermark so chaos-duplicated
  ``push_logs`` frames are idempotent.
- **Echo** — accepted lines fan out on CH_LOGS; the driver filters to
  its own job and prints ``(fn pid=N, node=M)``-prefixed lines under a
  per-source rate limit (``runtime/driver.py``).

Design invariant — STRICTLY BEST-EFFORT, same as the metrics plane:
capture is a few hundred nanoseconds of stamping on the emitting
process; all network IO happens on the raylet's monitor loop whose
pending queue is bounded (oldest entries dropped). A dropped, delayed,
duplicated, or partitioned log batch costs observability fidelity,
never throughput (asserted in ``tests/test_chaos_partitions.py``).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import sys
import threading
import time
from collections import OrderedDict, deque

# annex key prefix for the task -> log-offset segment registry
ANNEX_PREFIX = "logs/segments/"

# file line format (one header, tab, then the user text):
#   <ts> <o|e> <trace|-> <task|-> <name|-> <job|->\t<text>\n
# fields are single tokens (whitespace in name/job is folded) so the
# monitor parses with two splits and no regex on the hot path.
_HDR_FIELDS = 6


def _cfg_attr(name: str, default):
    """Config flag with an import-cycle-safe fallback."""
    try:
        from ray_tpu.utils.config import get_config

        return getattr(get_config(), name, default)
    except Exception:  # pragma: no cover - early-import fallback
        return default


# ambient task context: (task_id, name, job, trace_id) of the currently
# executing task/actor method — stamped onto every captured line
_task_ctx: contextvars.ContextVar[tuple | None] = \
    contextvars.ContextVar("ray_tpu_log_task", default=None)


def current_task_id() -> str | None:
    """Task id of the currently executing task/actor method (the log
    plane brackets every execution; ``runtime_context`` surfaces this)."""
    ctx = _task_ctx.get()
    return ctx[0] if ctx else None


def _tok(value) -> str:
    """One whitespace-free header token ('-' encodes None/empty)."""
    if not value:
        return "-"
    return "_".join(str(value).split()) or "-"


def _untok(token: str) -> str | None:
    return None if token == "-" else token


class _TeeStream:
    """File-like stand-in for sys.stdout/sys.stderr: complete lines go
    to the capture (stamped, rotated); everything else degrades to the
    original stream's behavior (fileno() still points at the Popen
    capture file, so C-level writes keep landing in <proc>.out/.err)."""

    def __init__(self, capture: "LogCapture", stream: str, orig):
        self._cap = capture
        self._stream = stream           # "o" | "e"
        self._orig = orig
        self._buf = ""
        self._lock = threading.Lock()

    def write(self, text) -> int:
        if not isinstance(text, str):
            text = str(text)
        with self._lock:
            self._buf += text
            if "\n" in self._buf:
                lines = self._buf.split("\n")
                self._buf = lines[-1]
                for line in lines[:-1]:
                    self._cap.emit(self._stream, line)
        return len(text)

    def writelines(self, lines):
        for line in lines:
            self.write(line)

    def flush(self):
        # line-buffered by design: a partial line flushes when its
        # newline arrives (or at close); emit() already hits the disk
        pass

    def close_partial(self):
        with self._lock:
            tail, self._buf = self._buf, ""
        if tail:
            self._cap.emit(self._stream, tail)

    def isatty(self) -> bool:
        return False

    def writable(self) -> bool:
        return True

    def fileno(self) -> int:
        return self._orig.fileno()

    @property
    def encoding(self):
        return getattr(self._orig, "encoding", "utf-8")

    @property
    def errors(self):
        return getattr(self._orig, "errors", "replace")

    @property
    def buffer(self):
        return getattr(self._orig, "buffer", self._orig)


class LogCapture:
    """Rotating, stamped capture file for one process.

    ``emit`` is the hot path: one time.time(), two contextvar reads,
    one %-format, one os.write — the bench_core ``log_overhead`` fence
    holds the amortized per-line delta under 3% of a remote call."""

    def __init__(self, proc: str, log_dir: str, *,
                 max_bytes: int | None = None,
                 rotate_count: int | None = None,
                 tail_lines: int | None = None):
        self.proc = proc
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, f"{proc}.log")
        self.max_bytes = int(max_bytes if max_bytes is not None
                             else _cfg_attr("log_max_bytes", 16 << 20))
        self.rotate_count = int(rotate_count if rotate_count is not None
                                else _cfg_attr("log_rotate_count", 3))
        tail_n = int(tail_lines if tail_lines is not None
                     else _cfg_attr("log_tail_lines", 50))
        self._lock = threading.Lock()
        self.epoch = 0
        self._fd: int | None = None
        self._size = 0
        self._pid = os.getpid()     # capture is created post-fork
        self._tracing = None        # lazily bound ray_tpu.util.tracing
        self._file_token = ""       # cached; refreshed on (re)open
        self._open_locked(first=True)
        # recent parsed records for the flight recorder / stuck-call
        # tails (bounded; slightly larger than the dump tail so a
        # task-filtered query still finds its lines)
        self._tail: deque = deque(maxlen=max(tail_n, 256))
        self._tail_n = tail_n
        # shippable records for SELF-ingesting processes (the external
        # GCS has no monitor tailing its files; _metrics_self_loop
        # drains this instead) — bounded, oldest dropped
        self._drain: deque = deque(maxlen=4096)
        # task -> (file, start, end) offset segments, published as a
        # metric annex after every bracketed execution
        self._segments: deque = deque(
            maxlen=max(1, int(_cfg_attr("log_segments_max", 128))))
        self.lines = 0
        self.dropped = 0

    # -- file management -----------------------------------------------

    def _open_locked(self, first: bool = False):
        if not first:
            self.epoch += 1
        self._file_token = f"{os.path.basename(self.path)}@{self.epoch}"
        flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
        self._fd = os.open(self.path, flags, 0o644)
        try:
            self._size = os.fstat(self._fd).st_size
        except OSError:  # pragma: no cover - fs race
            self._size = 0
        if self._size == 0:
            # epoch header: the monitor and the offset annex must agree
            # on which GENERATION an offset belongs to, so the live file
            # declares its own epoch instead of both sides counting
            # rotations independently
            hdr = f"#epoch {self.epoch}\n".encode()
            try:
                os.write(self._fd, hdr)
                self._size = len(hdr)
            except OSError:  # pragma: no cover - disk full
                pass

    def _rotate_locked(self):
        try:
            os.close(self._fd)
        except OSError:  # pragma: no cover
            pass
        if self.rotate_count <= 0:
            try:
                os.unlink(self.path)
            except OSError:  # pragma: no cover
                pass
        else:
            for i in range(self.rotate_count - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    try:
                        os.replace(src, f"{self.path}.{i + 1}")
                    except OSError:  # pragma: no cover
                        pass
            try:
                os.replace(self.path, f"{self.path}.1")
            except OSError:  # pragma: no cover
                pass
        self._open_locked()

    def file_token(self) -> str:
        """``<basename>@<epoch>`` — the identity offsets are scoped to
        (dedup watermarks and task segments both key on it)."""
        return self._file_token

    def offset(self) -> int:
        with self._lock:
            return self._size

    # -- the hot path --------------------------------------------------

    def emit(self, stream: str, text: str):
        """Stamp + append one complete line."""
        ts = time.time()
        ctx = _task_ctx.get()
        trace = None
        tracing = self._tracing
        if tracing is None:
            try:
                from ray_tpu.util import tracing
                self._tracing = tracing
            except Exception:  # pragma: no cover - early import
                tracing = None
        if tracing is not None:
            try:
                cur = tracing.current_context()
                if cur is not None:
                    trace = cur.trace_id
            except Exception:  # pragma: no cover - teardown
                pass
        task = name = job = None
        if ctx is not None:
            task, name, job = ctx[0], ctx[1], ctx[2]
            if trace is None:
                trace = ctx[3]
        data = "%f %s %s %s %s %s\t%s\n" % (
            ts, stream, _tok(trace), _tok(task), _tok(name), _tok(job),
            text)
        raw = data.encode("utf-8", "replace")
        with self._lock:
            if self._fd is None:
                self.dropped += 1
                return
            off = self._size
            try:
                os.write(self._fd, raw)
                self._size += len(raw)
            except OSError:  # pragma: no cover - disk full: drop
                self.dropped += 1
                return
            self.lines += 1
            # compact record tuple on the hot path; tail()/drain_records()
            # rebuild the dict shape on the (cold) read side
            rec = (ts, stream, text, trace, task, name, job,
                   self._file_token, off)
            self._tail.append(rec)
            self._drain.append(rec)
            if self._size >= self.max_bytes:
                self._rotate_locked()

    def _rec_dict(self, rec: tuple) -> dict:
        ts, stream, text, trace, task, name, job, file_token, off = rec
        return {"ts": ts, "stream": stream, "line": text,
                "trace": trace, "task": task, "name": name, "job": job,
                "file": file_token, "offset": off, "pid": self._pid}

    # -- task attribution ----------------------------------------------

    @contextlib.contextmanager
    def task_span(self, task_id: str, name: str, job: str | None,
                  trace_id: str | None):
        """Bracket one task/actor-method execution with begin/end
        offsets; the resulting segment rides the metric-annex registry
        so ``get_log(task_id=...)`` can serve exactly this slice."""
        with self._lock:
            start_file, start = self.file_token(), self._size
        token = _task_ctx.set((task_id, name, job, trace_id))
        try:
            yield
        finally:
            _task_ctx.reset(token)
            with self._lock:
                end_file, end = self.file_token(), self._size
            seg = {"task": task_id, "name": name, "proc": self.proc,
                   "file": start_file, "start": start,
                   "end_file": end_file, "end": end, "ts": time.time()}
            self._segments.append(seg)
            try:
                from ray_tpu.runtime import metrics_plane as _mp

                _mp.set_annex(ANNEX_PREFIX + self.proc,
                              list(self._segments))
            except Exception:  # pragma: no cover - teardown
                pass

    # -- reads ---------------------------------------------------------

    def tail(self, n: int | None = None, task_id: str | None = None
             ) -> list[dict]:
        n = self._tail_n if n is None else int(n)
        with self._lock:
            recs = list(self._tail)
        if task_id is not None:
            recs = [r for r in recs if r[4] == task_id]
        return [self._rec_dict(r) for r in recs[-n:]]

    def drain_records(self) -> list[dict]:
        """Pop records accumulated since the last drain (self-ingest
        path — the external GCS feeds its own LogStore from this)."""
        out = []
        with self._lock:
            while self._drain:
                out.append(self._drain.popleft())
        return [self._rec_dict(r) for r in out]

    def close(self):
        with self._lock:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:  # pragma: no cover
                pass


# ---------------------------------------------------------------------------
# process-wide install
# ---------------------------------------------------------------------------

_install_lock = threading.Lock()
_active: LogCapture | None = None
_tees: tuple | None = None


def install_capture(proc: str, log_dir: str | None = None,
                    **bounds) -> LogCapture | None:
    """Redirect this process's stdout/stderr through the stamped tee.
    Idempotent; returns the active capture (None when disabled)."""
    global _active, _tees
    with _install_lock:
        if _active is not None:
            return _active
        if not _cfg_attr("log_capture_enabled", True):
            return None
        if log_dir is None:
            log_dir = os.environ.get("RAY_TPU_LOG_DIR")
        if not log_dir:
            return None
        try:
            cap = LogCapture(proc, log_dir, **bounds)
        except OSError:
            return None
        out = _TeeStream(cap, "o", sys.stdout)
        err = _TeeStream(cap, "e", sys.stderr)
        sys.stdout, sys.stderr = out, err
        _active, _tees = cap, (out, err)
        return cap


def uninstall_capture():
    global _active, _tees
    with _install_lock:
        cap, _active = _active, None
        tees, _tees = _tees, None
    if tees is not None:
        for tee in tees:
            tee.close_partial()
        sys.stdout, sys.stderr = tees[0]._orig, tees[1]._orig
    if cap is not None:
        cap.close()


def active_capture() -> LogCapture | None:
    return _active


@contextlib.contextmanager
def task_context(task_id: str | None, name: str | None,
                 job: str | None = None, trace_id: str | None = None):
    """Bracket one execution for log attribution. Without an installed
    capture this still binds the ambient task context (so
    ``runtime_context`` can answer ``get_task_id`` in local mode) but
    records no segment — near-zero cost."""
    cap = _active
    if cap is not None and task_id:
        with cap.task_span(task_id, name or "?", job, trace_id):
            yield
        return
    token = _task_ctx.set((task_id, name, job, trace_id))
    try:
        yield
    finally:
        _task_ctx.reset(token)


@contextlib.contextmanager
def label_context(name: str):
    """Re-label the ambient task context (serve replicas stamp their
    deployment/replica tag over the generic actor-method name so echoed
    lines read ``(App/replica-ab12 pid=N, node=M)``)."""
    ctx = _task_ctx.get()
    if ctx is None:
        token = _task_ctx.set((None, name, None, None))
    else:
        token = _task_ctx.set((ctx[0], name, ctx[2], ctx[3]))
    try:
        yield
    finally:
        _task_ctx.reset(token)


def log_tail(n: int | None = None) -> list[dict]:
    """Last captured lines of THIS process (flight-recorder payload)."""
    cap = _active
    if cap is None:
        return []
    return cap.tail(n)


def recent_lines(task_id: str, n: int = 5) -> list[str]:
    """Last ``n`` captured lines attributed to ``task_id`` (stuck-call
    reports append these so a hung task's report is actionable)."""
    cap = _active
    if cap is None:
        return []
    return [r["line"] for r in cap.tail(n=n, task_id=task_id)]


def chrome_instant_events(records: list[dict] | None = None) -> list[dict]:
    """Attributed log lines as chrome://tracing instant events on the
    emitting task's trace lane (tid = trace_id, matching span lanes in
    ``util.tracing.to_chrome_trace``)."""
    if records is None:
        records = log_tail(None)
    events = []
    for r in records:
        if not r.get("trace"):
            continue
        events.append({
            "name": r["line"][:120],
            "cat": "log",
            "ph": "i",
            "s": "t",
            "ts": r["ts"] * 1e6,
            "pid": r.get("pid", 0),
            "tid": r["trace"],
            "args": {"task": r.get("task"), "stream": r.get("stream")},
        })
    return events


# ---------------------------------------------------------------------------
# line parsing (monitor side)
# ---------------------------------------------------------------------------

def parse_line(line: str):
    """One stamped capture line -> (ts, stream, trace, task, name, job,
    text), or None for the ``#epoch`` header. Unstamped lines (raw
    .out/.err files, pre-tee startup output) fall through with stamp
    defaults."""
    if line.startswith("#epoch "):
        return None
    hdr, sep, text = line.partition("\t")
    if sep:
        fields = hdr.split(" ")
        if len(fields) == _HDR_FIELDS:
            try:
                ts = float(fields[0])
            except ValueError:
                ts = None
            if ts is not None and fields[1] in ("o", "e"):
                return (ts, fields[1], _untok(fields[2]),
                        _untok(fields[3]), _untok(fields[4]),
                        _untok(fields[5]), text)
    return (time.time(), "o", None, None, None, None, line)


def parse_epoch(line: str) -> int | None:
    if line.startswith("#epoch "):
        try:
            return int(line[len("#epoch "):].strip())
        except ValueError:
            return None
    return None


# ---------------------------------------------------------------------------
# error grouping
# ---------------------------------------------------------------------------

# an "error line": a leveled ERROR/CRITICAL/FATAL message, or the final
# line of a traceback ("SomeError: ..."). Traceback BODY lines are not
# errors themselves — one uncaught exception must become ONE group.
_ERR_RE = re.compile(
    r"\b(ERROR|CRITICAL|FATAL)\b"
    r"|^\s*[A-Za-z_][\w.]*(Error|Exception|Interrupt|Exit)\b\s*(:|$)")
_NORM_NUM = re.compile(r"0x[0-9a-fA-F]+|\b[0-9a-f]{8,}\b|\d+")

# Last words of a fault-injected process death. FaultPlane._die writes
# this marker straight to fd 2 before os._exit/SIGKILL, so it is the one
# record a killed process leaves behind (no flight-recorder dump, no
# atexit). Keep in sync with fault_injection.CRASH_MARKER.
_CRASH_MARKER = "RAY_TPU_CRASH"


def is_error_line(text: str) -> bool:
    return bool(_ERR_RE.search(text)) or _CRASH_MARKER in text


def crash_point(text: str) -> str | None:
    """Crash-point name from a ``RAY_TPU_CRASH point=... rule=...`` line
    (None when the line is not a crash marker)."""
    pos = text.find(_CRASH_MARKER)
    if pos < 0:
        return None
    for tok in text[pos:].split():
        if tok.startswith("point="):
            return tok[len("point="):]
    return "?"


def error_signature(text: str) -> str:
    """Stable dedup key: numbers/ids folded, whitespace collapsed."""
    return " ".join(_NORM_NUM.sub("#", text).split())[:160]


# ---------------------------------------------------------------------------
# GCS-side store
# ---------------------------------------------------------------------------

def _pos_key(file_token: str, off: int) -> tuple:
    """Orderable (base, epoch, offset) position from a file@epoch token
    (lexicographic file comparison would put epoch 10 before 9)."""
    base, _, epoch = (file_token or "@").rpartition("@")
    try:
        return (base, int(epoch), off)
    except ValueError:
        return (base, 0, off)


class LogStore:
    """Bounded cluster log rings on the GCS.

    Per-proc recent-line rings answer ``get_log``; the error ring +
    signature-grouped table answers ``summarize_errors``. Ingest is
    idempotent per (proc, file@epoch, offset) watermark, so duplicated
    push frames (chaos, monitor retry after a lost ack) neither
    double-store nor double-echo."""

    def __init__(self, lines_per_proc: int = 2000,
                 error_lines: int = 2000, error_groups: int = 256,
                 max_procs: int = 512):
        self._lock = threading.Lock()
        self._lines_per_proc = max(16, int(lines_per_proc))
        self._max_procs = max(1, int(max_procs))
        self._procs: "OrderedDict[str, dict]" = OrderedDict()
        self._errors: deque = deque(maxlen=max(16, int(error_lines)))
        self._groups: "OrderedDict[str, dict]" = OrderedDict()
        self._max_groups = max(8, int(error_groups))
        self.ingested = 0
        self.deduped = 0

    def _proc_locked(self, proc: str) -> dict:
        ent = self._procs.get(proc)
        if ent is None:
            ent = self._procs[proc] = {
                "ring": deque(maxlen=self._lines_per_proc),
                "watermarks": {},        # file@epoch -> max offset seen
                "node": None, "pid": 0, "last_ts": 0.0}
            while len(self._procs) > self._max_procs:
                self._procs.popitem(last=False)
        else:
            self._procs.move_to_end(proc)
        return ent

    def ingest(self, node_id: str, entries: list) -> list:
        """Store new lines; returns the accepted entries (same wire
        shape, duplicates stripped) for CH_LOGS fan-out."""
        accepted = []
        with self._lock:
            for entry in entries or []:
                proc = entry.get("proc") or "?"
                file_token = entry.get("file") or "?"
                ent = self._proc_locked(proc)
                ent["node"] = node_id
                if entry.get("pid"):
                    ent["pid"] = entry["pid"]
                wm = ent["watermarks"].get(file_token, -1)
                fresh = []
                for rec in entry.get("lines") or []:
                    # rec: (offset, ts, stream, text, trace, task,
                    #       name, job)
                    try:
                        off = int(rec[0])
                    except (TypeError, ValueError, IndexError):
                        continue
                    if off <= wm:
                        self.deduped += 1
                        continue
                    wm = off
                    fresh.append(rec)
                    stored = {"node": node_id, "proc": proc,
                              "pid": entry.get("pid", 0),
                              "file": file_token, "offset": off,
                              "ts": rec[1], "stream": rec[2],
                              "line": rec[3], "trace": rec[4],
                              "task": rec[5], "name": rec[6],
                              "job": rec[7]}
                    ent["ring"].append(stored)
                    ent["last_ts"] = max(ent["last_ts"], rec[1] or 0.0)
                    self.ingested += 1
                    if is_error_line(stored["line"]):
                        self._errors.append(stored)
                        self._group_locked(stored)
                ent["watermarks"][file_token] = wm
                if len(ent["watermarks"]) > 64:
                    # rotation churn: forget the oldest generations
                    for k in list(ent["watermarks"])[:-32]:
                        del ent["watermarks"][k]
                if fresh:
                    accepted.append({**entry, "lines": fresh})
        return accepted

    def _group_locked(self, rec: dict):
        line = rec["line"]
        point = crash_point(line)
        if point is not None:
            # group crash deaths by crash point, not by raw text — the
            # marker may ride the tail of an unterminated stdout line,
            # and pid/rule ids vary per death
            pos = line.find(_CRASH_MARKER)
            sig = error_signature(line[pos:])
            kind = "crash"
        else:
            sig = error_signature(line)
            kind = "error"
        g = self._groups.get(sig)
        if g is None:
            g = self._groups[sig] = {
                "signature": sig, "kind": kind, "sample": line,
                "count": 0,
                "first_ts": rec["ts"], "last_ts": rec["ts"],
                "procs": set(), "traces": set(), "tasks": set()}
            if point is not None:
                g["crash_point"] = point
            while len(self._groups) > self._max_groups:
                self._groups.popitem(last=False)
        else:
            self._groups.move_to_end(sig)
        g["count"] += 1
        g["first_ts"] = min(g["first_ts"], rec["ts"])
        g["last_ts"] = max(g["last_ts"], rec["ts"])
        g["procs"].add(rec["proc"])
        if rec.get("trace") and len(g["traces"]) < 8:
            g["traces"].add(rec["trace"])
        if rec.get("task") and len(g["tasks"]) < 8:
            g["tasks"].add(rec["task"])

    # -- queries -------------------------------------------------------

    def _resolve_proc_locked(self, proc: str) -> str | None:
        if proc in self._procs:
            return proc
        hits = [p for p in self._procs
                if p.startswith(proc) or p.endswith(proc)
                or p == f"worker-{proc}"]
        return hits[0] if len(hits) == 1 else None

    def tail(self, proc: str, n: int = 100,
             after: tuple | None = None) -> dict:
        with self._lock:
            name = self._resolve_proc_locked(proc)
            if name is None:
                return {"proc": proc, "lines": [],
                        "error": f"no logs for process {proc!r}"}
            ent = self._procs[name]
            recs = list(ent["ring"])
        if after:
            cursor = _pos_key(after[0], int(after[1]))
            recs = [r for r in recs
                    if _pos_key(r["file"], r["offset"]) > cursor]
        recs = recs[-max(0, int(n)):]
        return {"proc": name, "node": ent["node"], "pid": ent["pid"],
                "lines": recs}

    def segment(self, seg: dict) -> dict:
        """Exactly the lines inside one task's offset segment (epoch-
        aware: a rotation mid-task spans two generations)."""

        lo = _pos_key(seg.get("file"), int(seg.get("start", 0)))
        hi = _pos_key(seg.get("end_file") or seg.get("file"),
                      int(seg.get("end", 0)))
        with self._lock:
            name = self._resolve_proc_locked(seg.get("proc") or "")
            if name is None:
                return {"proc": seg.get("proc"), "lines": [],
                        "error": "segment's process has no stored logs"}
            recs = [r for r in self._procs[name]["ring"]
                    if lo <= _pos_key(r["file"], r["offset"]) < hi]
        return {"proc": name, "task": seg.get("task"),
                "name": seg.get("name"), "lines": recs,
                "segment": {k: seg.get(k) for k in
                            ("file", "start", "end_file", "end")}}

    def list(self) -> dict:
        with self._lock:
            procs = {
                proc: {"node": ent["node"], "pid": ent["pid"],
                       "lines": len(ent["ring"]),
                       "last_ts": ent["last_ts"],
                       "files": sorted(ent["watermarks"])}
                for proc, ent in self._procs.items()}
        return {"procs": procs, "ingested": self.ingested,
                "deduped": self.deduped}

    def summarize_errors(self, last_s: float | None = None) -> list[dict]:
        now = time.time()
        with self._lock:
            groups = [dict(g) for g in self._groups.values()
                      if last_s is None or now - g["last_ts"] <= last_s]
        for g in groups:
            g["procs"] = sorted(g["procs"])
            g["traces"] = sorted(g["traces"])
            g["tasks"] = sorted(g["tasks"])
        groups.sort(key=lambda g: (-g["count"], -g["last_ts"]))
        return groups
